"""Skew-aware exchange planning (ops/skew.py; conf slotQuotaRows).

Two layers of pinning: planner geometry as pure-host property tests (quota
bucketing, chunk row conservation, slice/reassemble round-trip vs a direct
oracle), and transport bit-equality — a quota-capped cluster run must produce
byte-for-byte the receive state of the default single-shot run, across all
three host_recv_modes, multi-round spill, and device staging.  The quota only
reshapes staging/wire geometry; it must never touch bytes.
"""

import os

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.ops.skew import (
    ExchangePlan,
    chunk_size_rows,
    pad_rows_pow2,
    piece_slices,
    plan_exchange,
    quota_slot_rows,
    reassemble_round,
    slice_subround,
    staging_occupancy,
)
from sparkucx_tpu.transport.tpu import TpuShuffleCluster
from sparkucx_tpu.utils.stats import StatsAggregator

N_EXEC = 4


# ----------------------------------------------------------------------
# planner geometry (pure host, no mesh)


class TestQuotaSlotRows:
    def test_pow2_bucket(self):
        assert quota_slot_rows(100, 0) == 128  # no quota: pow2 of the slot
        assert quota_slot_rows(64, 0) == 64  # pow2 slot is a fixed point
        assert quota_slot_rows(1, 0) == 1

    def test_cap_then_bucket(self):
        assert quota_slot_rows(100, 64) == 64
        assert quota_slot_rows(100, 50) == 64  # cap 50, then pow2
        assert quota_slot_rows(8, 1000) == 8  # quota above slot: inert

    def test_rejects_nonpositive_slot(self):
        with pytest.raises(ValueError, match="slot_rows"):
            quota_slot_rows(0, 16)


class TestPlanExchange:
    def test_chunk_counts_cover_hottest_lane(self):
        plan = plan_exchange([100, 0, 5], 128, 32)
        assert plan.slot_rows == 32
        assert plan.chunks_per_round == (4, 1, 1)  # ceil(100/32), min 1
        assert plan.num_subrounds == 6

    def test_empty_round_still_runs_one_subround(self):
        # SPMD lockstep: every executor must dispatch every collective
        plan = plan_exchange([0], 128, 32)
        assert plan.chunks_per_round == (1,)

    def test_subround_order_chunk_major(self):
        plan = ExchangePlan(slot_rows=16, chunks_per_round=(2, 1))
        assert plan.subrounds() == [(0, 0, 2), (0, 1, 2), (1, 0, 1)]

    def test_staged_rows_reduction_on_zipf_skew(self):
        """The acceptance geometry: on a Zipf-skewed matrix whose hottest lane
        sits just past a pow2 boundary, the quota plan stages (and, dense,
        wires) strictly fewer rows than the single-shot pow2 bucket."""
        from sparkucx_tpu.perf.benchmark import zipf_size_matrix

        n = 8
        sizes = zipf_size_matrix(n, 2200, 1.2)
        assert int(sizes.max()) == 2200
        slot = quota_slot_rows(int(sizes.max()), 0)  # single-shot bucket: 4096
        quota = quota_slot_rows(slot, int(np.ceil(sizes.mean())))
        plan = plan_exchange([int(sizes.max())], slot, quota)
        single_shot = n * n * slot
        assert plan.staged_rows(n) < single_shot
        # quota plan covers the data: chunks * slot >= hottest lane
        assert plan.chunks_per_round[0] * plan.slot_rows >= int(sizes.max())


class TestChunkGeometry:
    def test_row_conservation_and_cap(self, rng):
        """Summing chunk_size_rows over a plan's chunks reproduces the size
        row exactly, and no chunk exceeds the quota slot."""
        for _ in range(20):
            n = int(rng.integers(1, 9))
            slot = int(rng.integers(1, 200))
            sizes = rng.integers(0, slot + 1, size=n).astype(np.int32)
            q = quota_slot_rows(slot, int(rng.integers(1, slot + 1)))
            nchunks = plan_exchange([int(sizes.max())], slot, q).chunks_per_round[0]
            chunks = [chunk_size_rows(sizes, c, q) for c in range(nchunks)]
            assert all(int(c.max(initial=0)) <= q for c in chunks)
            np.testing.assert_array_equal(np.sum(chunks, axis=0), sizes)

    def test_slice_reassemble_matches_direct_oracle(self, rng):
        """Sender-side slicing + a simulated compacting exchange + receiver
        reassembly reproduces, byte for byte, the tight sender-major buffer a
        single-shot exchange produces (sliced straight from the payloads)."""
        n, lane = 5, 4
        row_bytes = lane * 4
        slot = 23
        q = 8  # ceil(23/8) = 3 sub-rounds
        nchunks = plan_exchange([slot], slot, q).chunks_per_round[0]
        sizes = rng.integers(0, slot + 1, size=(n, n)).astype(np.int32)
        payloads = [
            rng.integers(-100, 100, size=(n * slot, lane), dtype=np.int32)
            for _ in range(n)
        ]
        sub_size_mats = [
            np.stack([chunk_size_rows(sizes[i], c, q) for i in range(n)])
            for c in range(nchunks)
        ]
        for j in range(n):
            # what the dense lowering compacts for consumer j in sub-round c
            sub_shards = []
            for c in range(nchunks):
                pieces = [
                    slice_subround(payloads[i], n, c, q)[
                        j * q : j * q + int(sub_size_mats[c][i, j])
                    ]
                    for i in range(n)
                ]
                sub_shards.append(
                    np.concatenate(pieces).reshape(-1).view(np.uint8)
                )
            got = reassemble_round(
                sub_shards, [m[:, j] for m in sub_size_mats], row_bytes
            )
            want = np.concatenate(
                [payloads[i][j * slot : j * slot + int(sizes[i, j])] for i in range(n)]
            ).reshape(-1).view(np.uint8)
            assert bytes(got) == bytes(want), f"consumer {j} diverged"

    def test_slice_subround_all_pad_window(self):
        p = np.arange(2 * 4 * 3, dtype=np.int32).reshape(8, 3)  # n=2, slot=4
        out = slice_subround(p, 2, chunk=2, quota_slot=2)  # window [4, 6) >= slot
        assert out.shape == (4, 3) and not out.any()

    def test_slice_subround_rejects_ragged_payload(self):
        with pytest.raises(ValueError, match="not a multiple"):
            slice_subround(np.zeros((7, 4), dtype=np.int32), 2, 0, 2)

    def test_piece_slices_skips_zero_rows(self):
        subs = [np.array([2, 0, 1]), np.array([0, 0, 3])]
        assert piece_slices(subs) == [(0, 0, 2), (0, 2, 1), (1, 0, 3)]

    def test_reassemble_empty_is_empty(self):
        out = reassemble_round([np.zeros(0, np.uint8)], [np.array([0, 0])], 16)
        assert out.dtype == np.uint8 and out.size == 0

    def test_staging_occupancy(self):
        used, padded = staging_occupancy(np.array([3, 0, 5]), 8)
        assert (used, padded) == (8, 16)

    def test_pad_rows_pow2(self):
        a = np.ones((5, 2), dtype=np.int32)
        out = pad_rows_pow2(a)
        assert out.shape == (8, 2) and int(out.sum()) == 10
        same = pad_rows_pow2(np.ones((4, 2), dtype=np.int32))
        assert same.shape == (4, 2)


# ----------------------------------------------------------------------
# conf surface


class TestConf:
    def test_spark_key_parses(self):
        conf = TpuShuffleConf.from_spark_conf(
            {"spark.shuffle.tpu.slotQuotaRows": "64"}
        )
        assert conf.slot_quota_rows == 64

    def test_negative_rejected(self):
        with pytest.raises(ValueError, match="slot_quota_rows"):
            TpuShuffleConf(slot_quota_rows=-1).validate()


# ----------------------------------------------------------------------
# padding telemetry


class TestPaddingTelemetry:
    def test_record_rows_and_padding_fraction(self):
        stats = StatsAggregator()
        stats.record_rows("exchange.lanes", used_rows=6, padded_rows=2)
        stats.record_rows("exchange.lanes", used_rows=2, padded_rows=6)
        s = stats.summary("exchange.lanes")
        assert (s.used_rows, s.padded_rows) == (8, 8)
        assert s.padding_fraction == 0.5
        assert "padding=50.0%" in stats.report()

    def test_padding_fraction_zero_when_unpopulated(self):
        from sparkucx_tpu.utils.stats import StatsSummary

        assert StatsSummary().padding_fraction == 0.0

    def test_pipeline_drain_carries_occupancy(self):
        from sparkucx_tpu.transport.pipeline import RoundPipeline

        stats = StatsAggregator()
        pipe = RoundPipeline(
            1,
            lambda rnd: rnd,
            lambda rnd, t: t,
            name="p",
            stats=stats,
            result_rows=lambda r: (10, 6),
        )
        pipe.run(2)
        s = stats.summary("p.drain")
        assert (s.used_rows, s.padded_rows) == (20, 12)
        assert s.padding_fraction == pytest.approx(12 / 32)


# ----------------------------------------------------------------------
# pack_chunks_slots tail hygiene (np.empty fast path)


class TestPackChunksSlots:
    def test_final_row_tail_zeroed(self):
        from sparkucx_tpu.ops.exchange import pack_chunks_slots

        row_bytes = 16
        chunks = [b"\xff" * 5, b"", b"\xaa" * 16, b"\xbb" * 17]
        buf, sizes = pack_chunks_slots(chunks, slot_rows=4, row_bytes=row_bytes)
        np.testing.assert_array_equal(sizes, [1, 0, 1, 2])
        flat = buf.reshape(-1).view(np.uint8)
        for j, chunk in enumerate(chunks):
            start = j * 4 * row_bytes
            rows = -(-len(chunk) // row_bytes)
            assert flat[start : start + len(chunk)].tobytes() == chunk
            # the used final row's tail is zero (it DOES reach receivers)
            tail = flat[start + len(chunk) : start + rows * row_bytes]
            assert not tail.any()

    def test_oversized_chunk_rejected(self):
        from sparkucx_tpu.ops.exchange import pack_chunks_slots

        with pytest.raises(ValueError, match="exceeds slot"):
            pack_chunks_slots([b"x" * 100], slot_rows=2, row_bytes=16)


# ----------------------------------------------------------------------
# transport bit-equality: quota vs default through the full cluster


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


def _write_skewed(cluster, shuffle_id, M, R, seed=77):
    """Zipf-flavored writes: reduce 0 is hot (big blocks), the rest cold —
    the skew the quota exists to absorb.  Same seed -> identical streams."""
    meta = cluster.create_shuffle(shuffle_id, M, R)
    rng = np.random.default_rng(seed)
    oracle = {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(shuffle_id, m)
        for r in range(R):
            size = int(rng.integers(2000, 3000)) if r == 0 else int(rng.integers(1, 300))
            payload = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    return meta, oracle


def _fetch_all(cluster, meta, shuffle_id, M, R, oracle):
    for r in range(R):
        consumer = meta.owner_of_reduce(r)
        t = cluster.transport(consumer)
        bufs = [_buf(8192) for _ in range(M)]
        reqs = t.fetch_blocks_by_block_ids(
            consumer, [ShuffleBlockId(shuffle_id, m, r) for m in range(M)],
            bufs, [None] * M,
        )
        for m in range(M):
            res = reqs[m].wait(5)
            assert res.status == OperationStatus.SUCCESS, str(res.error)
            assert bufs[m].host_view()[: bufs[m].size].tobytes() == oracle[(m, r)]


def _conf(quota, mode="array", **kw):
    return TpuShuffleConf(
        staging_capacity_per_executor=N_EXEC * 4096,
        block_alignment=128,
        num_executors=N_EXEC,
        host_recv_mode=mode,
        slot_quota_rows=quota,
        **kw,
    )


def _exchange(conf, M=3 * N_EXEC, R=8):
    cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
    meta, oracle = _write_skewed(cluster, 0, M, R)
    cluster.run_exchange(0)
    return cluster, meta, oracle


class TestClusterBitEquality:
    def test_array_mode_matches_default_bitwise(self):
        """Quota-capped multi-round exchange vs the single-shot default, same
        seeded writes: identical logical receive sizes, and every consumer's
        tight shard is a byte-exact prefix of the default's receive buffer."""
        base_cluster, base_meta, oracle = _exchange(_conf(0))
        q_cluster, q_meta, _ = _exchange(_conf(8))
        assert len(base_meta.recv_sizes) > 1, "should spill multiple rounds"
        assert len(q_meta.recv_sizes) == len(base_meta.recv_sizes)
        for rnd in range(len(base_meta.recv_sizes)):
            np.testing.assert_array_equal(
                q_meta.recv_sizes[rnd], base_meta.recv_sizes[rnd]
            )
            for j in range(N_EXEC):
                tight = q_meta.recv_shards[rnd][j]
                used = int(base_meta.recv_sizes[rnd][j].sum()) * 128
                assert tight.nbytes == used  # quota shards carry no padding
                assert bytes(tight) == bytes(base_meta.recv_shards[rnd][j][:used])
        _fetch_all(q_cluster, q_meta, 0, 3 * N_EXEC, 8, oracle)
        # the quota engine ran chunked: padding telemetry was recorded
        drain = q_cluster.stats.summary("exchange.pipeline.drain")
        assert drain.used_rows > 0 and drain.padded_rows > 0

    def test_quota_zero_is_default_path(self):
        """slotQuotaRows=0 (the default) must never enter the quota engine."""
        cluster, meta, oracle = _exchange(_conf(0))
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    def test_memmap_mode(self, tmp_path):
        conf = _conf(8, mode="memmap", spill_dir=str(tmp_path))
        cluster, meta, oracle = _exchange(conf)
        for rnd in meta.recv_shards:
            for shard in rnd:
                # tight shards spill to read-only mappings; a consumer that
                # received nothing keeps an empty array (nothing to map)
                assert isinstance(shard, np.memmap) or shard.nbytes == 0
                if isinstance(shard, np.memmap):
                    assert not shard.flags.writeable
        spilled = [p for p, _ in meta.recv_spill_paths]
        assert spilled and all(os.path.exists(p) for p in spilled)
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)
        cluster.remove_shuffle(0)
        assert not any(os.path.exists(p) for p in spilled), "spill leaked"

    def test_device_mode(self):
        conf = _conf(8, mode="device", keep_device_recv=True)
        cluster, meta, oracle = _exchange(conf)
        assert meta.recv_shards is None, "device mode must keep no host copy"
        assert meta.recv_device is not None
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    def test_device_staging_rounds(self):
        """Device-sealed rounds take the on-device chunk-slicing arm of the
        quota submit (slice_subround with xp=jnp)."""
        conf = _conf(8, device_staging=True, gather_impl="xla")
        cluster, meta, oracle = _exchange(conf)
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    def test_quota_above_slot_matches_default(self):
        """A quota larger than the staging slot plans one chunk per round —
        geometry identical to the default bucket, bytes identical too."""
        base_cluster, base_meta, oracle = _exchange(_conf(0))
        q_cluster, q_meta, _ = _exchange(_conf(1 << 20))
        assert len(q_meta.recv_sizes) == len(base_meta.recv_sizes)
        for rnd in range(len(base_meta.recv_sizes)):
            np.testing.assert_array_equal(
                q_meta.recv_sizes[rnd], base_meta.recv_sizes[rnd]
            )
        _fetch_all(q_cluster, q_meta, 0, 3 * N_EXEC, 8, oracle)


class TestStoreOccupancy:
    def test_round_max_rows_and_occupancy(self, rng):
        """The store-side planner inputs: per-round hottest-lane rows and the
        (used, padded) occupancy pairs the telemetry reports."""
        conf = _conf(0)
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        meta, _ = _write_skewed(cluster, 0, 3 * N_EXEC, 8)
        store = cluster.transport(0).store
        maxes = store.round_max_rows(0)
        assert maxes and all(m >= 0 for m in maxes)
        occ = store.stats(0)["round_occupancy"]
        assert len(occ) == len(maxes)
        for used, padded in occ:
            assert used >= 0 and padded >= 0
