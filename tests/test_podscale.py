"""Pod-scale compile/execute checks: the exchange and the full shuffle stack
at 16 and 64 virtual executors (BASELINE.md north star: "scaling efficiency
4→64 chips" — no multi-chip hardware exists here, so what CAN be validated is
that the sharded programs compile and run correctly at pod device counts,
including the 4-slice hierarchical route at 16).

Each case runs in a subprocess because XLA_FLAGS' virtual device count is
parsed once per process (the suite's conftest pins 8)."""

import os
import subprocess
import sys
import textwrap

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PRELUDE = textwrap.dedent(
    """
    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count={n}"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import sys; sys.path.insert(0, {root!r})
    import numpy as np
    """
)


def _run(n, body, timeout=240):
    code = PRELUDE.format(n=n, root=ROOT) + textwrap.dedent(body)
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=timeout, cwd=ROOT,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-2000:]}"
    assert "PODSCALE OK" in r.stdout, r.stdout


class TestPodScale:
    def test_flat_exchange_64_executors(self):
        """One collective over a 64-executor mesh, skewed sizes vs oracle."""
        _run(64, """
    from jax.sharding import NamedSharding, PartitionSpec as P
    from sparkucx_tpu.ops.exchange import ExchangeSpec, build_exchange, make_mesh

    n, slot = 64, 4
    spec = ExchangeSpec(num_executors=n, send_rows=n * slot, recv_rows=n * slot, lane=128)
    mesh = make_mesh(n)
    fn = build_exchange(mesh, spec)
    rng = np.random.default_rng(0)
    sizes = rng.integers(0, slot + 1, size=(n, n)).astype(np.int32)
    data = rng.integers(-100, 100, size=(n * n * slot, 128), dtype=np.int32)
    sh = NamedSharding(mesh, P("ex", None))
    recv, rs = fn(jax.device_put(data, sh), jax.device_put(sizes, sh))
    recv_h, rs_h = np.asarray(recv), np.asarray(rs)
    assert (rs_h == sizes.T).all(), "receive-size matrix mismatch"
    # oracle: receiver j gets, sender-major, each sender i's slot-j prefix
    shards = data.reshape(n, n, slot, 128)
    for j in range(0, n, 13):
        expect = np.concatenate(
            [shards[i, j, : sizes[i, j]] for i in range(n)]
            + [np.zeros((n * slot - sizes[:, j].sum(), 128), np.int32)]
        )
        got = recv_h.reshape(n, n * slot, 128)[j]
        assert (got == expect).all(), f"receiver {j} mismatch"
    print("PODSCALE OK")
    """)

    def test_full_stack_16_executors_4_slices(self):
        """The whole cluster stack (staging -> commit -> hierarchical 4x4
        two-phase exchange -> fetch) at 16 executors vs oracle."""
        _run(16, """
    from jax.sharding import Mesh
    from sparkucx_tpu.config import TpuShuffleConf
    from sparkucx_tpu.transport.tpu import TpuShuffleCluster

    n = 16
    mesh = Mesh(np.array(jax.devices()[:n]), ("ex",))
    conf = TpuShuffleConf(
        staging_capacity_per_executor=n * 2048, block_alignment=128,
        num_executors=n, num_slices=4,
    )
    cluster = TpuShuffleCluster(conf, mesh=mesh)
    M, R = n, 2 * n
    meta = cluster.create_shuffle(0, M, R)
    rng = np.random.default_rng(1)
    oracle = {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(0, m)
        for r in range(R):
            payload = rng.integers(0, 256, size=int(rng.integers(1, 300)), dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    cluster.run_exchange(0)
    for (m, r), expect in oracle.items():
        consumer = meta.owner_of_reduce(r)
        view, ln = cluster.locate_received_block(consumer, 0, m, r)
        assert view.tobytes() == expect, f"mismatch map={m} reduce={r}"
    cluster.remove_shuffle(0)
    print("PODSCALE OK")
    """)

    def test_distributed_sort_32_executors(self):
        """Sample sort over 32 executors vs the host oracle."""
        _run(32, """
    from sparkucx_tpu.ops.exchange import make_mesh
    from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_distributed_sort

    n, cap = 32, 64
    mesh = make_mesh(n)
    spec = SortSpec(num_executors=n, capacity=cap, recv_capacity=3 * cap, width=2,
                    samples_per_shard=n)
    rng = np.random.default_rng(2)
    total = n * cap - 37  # uneven fill
    keys = rng.integers(0, 1 << 32, size=total, dtype=np.uint64).astype(np.uint32)
    payload = rng.integers(-50, 50, size=(total, 2)).astype(np.int32)
    sk, sp = run_distributed_sort(mesh, spec, keys, payload)
    ek, ep = oracle_sort(keys, payload)
    assert (sk == ek).all()
    assert (sp == ep).all()
    print("PODSCALE OK")
    """)
