"""Plan-driven exchange: planners, optimization passes, golden equivalence.

Three layers of pinning, mirroring the plan stack's layering:

* **Planner mapping** (pure host) — ``StaticPlanner`` maps the legacy conf
  knobs (``spark.shuffle.tpu.slotQuotaRows`` & co.) 1:1 onto an
  ``ExchangePlan``; the default conf's plan is the golden serve-plane tuple
  (codec off, one stream, no hedge) that leaves wire framing byte-identical
  to the pre-plan engines.  ``AdaptivePlanner`` layers deterministic
  telemetry rules on top (``spark.shuffle.tpu.planner.mode`` /
  ``spark.shuffle.tpu.planner.optimize`` /
  ``spark.shuffle.tpu.planner.targetPaddingFraction`` /
  ``spark.shuffle.tpu.planner.minQuotaRows``) — and its COLLECTIVE schedule
  must be a pure function of the agreed geometry, never local telemetry
  (the SPMD lockstep invariant).
* **Optimization passes** — pure plan->plan rewrites preserve coverage
  (chunks x slot still covers every round's hottest lane) so bytes never
  change; only schedule geometry does.
* **Transport bit-equality** — a plan-driven cluster run (optimize on,
  adaptive mode, pallas lowering, each host_recv_mode) must reproduce the
  default run's receive state byte for byte, and ``build_plan_exchange``
  must lower to the exact compiled exchanges the per-variant builders
  produce (stock / pallas / quantized).
"""

import dataclasses

import jax
import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.obs.metrics import MetricSample
from sparkucx_tpu.ops.planner import (
    DEFAULT_PASSES,
    AdaptivePlanner,
    PlanContext,
    PlanSignals,
    StaticPlanner,
    make_planner,
    optimize_plan,
    pass_coalesce_chunks,
    pass_pow2_bucket,
    pass_reorder_rounds,
)
from sparkucx_tpu.ops.skew import ExchangePlan, plan_exchange, quota_slot_rows
from sparkucx_tpu.transport.executor import (
    HOST_RECV_MODES,
    build_plan_exchange,
    validate_host_recv_mode,
)
from sparkucx_tpu.transport.tpu import TpuShuffleCluster
from sparkucx_tpu.utils.trace import TRACER

N_EXEC = 4


def _ctx(
    slot=100,
    maxes=(70, 10),
    used=0,
    n=N_EXEC,
    signals=PlanSignals(),
    platform="cpu",
):
    return PlanContext(
        num_executors=n,
        staging_slot_rows=slot,
        round_max_rows=tuple(maxes),
        used_rows_total=used,
        row_bytes=128,
        platform=platform,
        signals=signals,
    )


# ----------------------------------------------------------------------
# StaticPlanner: legacy conf knobs -> plan, 1:1


class TestStaticPlannerMapping:
    def test_default_conf_single_shot_golden(self):
        """The default conf's plan IS the historical engine: pow2 slot
        bucket, one chunk per round, single-shot drain, and the serve-plane
        fields that keep wire frames byte-identical (codec off, one stream,
        no hedge, no quantization)."""
        conf = TpuShuffleConf()
        plan = StaticPlanner(conf).plan(_ctx(slot=100, maxes=(70, 10)))
        assert plan.slot_rows == quota_slot_rows(100, 0) == 128
        assert plan.chunks_per_round == (1, 1)
        assert plan.single_shot is True
        assert plan.round_order == ()
        # serve-plane golden tuple (the wire-framing pin)
        assert (plan.streams, plan.codec, plan.hedge_ms) == (1, "off", 0)
        assert (plan.quantize_mode, plan.quantize_block) == ("off", 128)
        # every remaining field copies its conf knob verbatim
        assert plan.lowering == conf.exchange_impl
        assert plan.pipeline_depth == conf.pipeline_depth

    def test_quota_conf_maps_to_plan_exchange(self):
        conf = TpuShuffleConf(slot_quota_rows=32)
        maxes = (100, 0, 5)
        plan = StaticPlanner(conf).plan(_ctx(slot=128, maxes=maxes))
        base = plan_exchange(maxes, 128, 32)
        assert (plan.slot_rows, plan.chunks_per_round) == (
            base.slot_rows,
            base.chunks_per_round,
        )
        assert plan.single_shot is False
        assert plan.round_order == ()  # optimize is off by default

    def test_quota_above_slot_single_launch_geometry(self):
        conf = TpuShuffleConf(slot_quota_rows=1 << 20)
        plan = StaticPlanner(conf).plan(_ctx(slot=100, maxes=(70, 10)))
        assert plan.slot_rows == 128
        assert plan.chunks_per_round == (1, 1)

    def test_no_rounds_still_plans_one(self):
        plan = StaticPlanner(TpuShuffleConf()).plan(_ctx(maxes=()))
        assert plan.chunks_per_round == (1,)
        assert plan.single_shot is True

    def test_serve_plane_knobs_copied_verbatim(self):
        conf = TpuShuffleConf(
            wire_streams=4,
            wire_compress_codec="rle",
            quantize_mode="int8",
            quantize_block_size=64,
            fetch_hedge_ms=7,
            pipeline_depth=3,
            exchange_impl="pallas",
        )
        plan = StaticPlanner(conf).plan(_ctx())
        assert plan.streams == 4
        assert plan.codec == "rle"
        assert (plan.quantize_mode, plan.quantize_block) == ("int8", 64)
        assert plan.hedge_ms == 7
        assert plan.pipeline_depth == 3
        assert plan.lowering == "pallas"

    def test_optimize_on_reorders_rounds(self):
        conf = TpuShuffleConf(slot_quota_rows=16, planner_optimize=True)
        plan = StaticPlanner(conf).plan(_ctx(slot=64, maxes=(48, 1)))
        # round 1 (1 chunk) is lighter than round 0 (3 chunks): submits first
        assert plan.chunks_per_round == (3, 1)
        assert plan.round_order == (1, 0)


# ----------------------------------------------------------------------
# conf knobs: spark-key parsing + validation + planner dispatch


class TestPlannerConfKnobs:
    def test_spark_keys_parse(self):
        conf = TpuShuffleConf.from_spark_conf(
            {
                "spark.shuffle.tpu.planner.mode": "adaptive",
                "spark.shuffle.tpu.planner.optimize": "true",
                "spark.shuffle.tpu.planner.targetPaddingFraction": "0.25",
                "spark.shuffle.tpu.planner.minQuotaRows": "128",
            }
        )
        assert conf.planner_mode == "adaptive"
        assert conf.planner_optimize is True
        assert conf.planner_target_padding == 0.25
        assert conf.planner_min_quota_rows == 128

    def test_defaults_are_off_path(self):
        conf = TpuShuffleConf()
        assert conf.planner_mode == "static"
        assert conf.planner_optimize is False

    def test_validate_rejects_unknown_mode(self):
        with pytest.raises(ValueError, match="planner_mode"):
            TpuShuffleConf(planner_mode="bogus").validate()

    def test_validate_rejects_bad_padding_target(self):
        with pytest.raises(ValueError, match="planner_target_padding"):
            TpuShuffleConf(planner_target_padding=1.5).validate()

    def test_validate_rejects_bad_min_quota(self):
        with pytest.raises(ValueError, match="planner_min_quota_rows"):
            TpuShuffleConf(planner_min_quota_rows=0).validate()

    def test_make_planner_dispatch(self):
        assert isinstance(make_planner(TpuShuffleConf()), StaticPlanner)
        assert isinstance(
            make_planner(TpuShuffleConf(planner_mode="adaptive")), AdaptivePlanner
        )


# ----------------------------------------------------------------------
# optimization passes: coverage-preserving geometry rewrites


class TestOptimizationPasses:
    def test_pow2_bucket_rebuckets_hand_built_plan(self):
        plan = ExchangePlan(slot_rows=100, chunks_per_round=(2,))
        out = pass_pow2_bucket(plan, _ctx(slot=100, maxes=(200,)))
        assert out.slot_rows == 128
        # coverage preserved: chunks x slot still covers the implied need
        assert out.chunks_per_round[0] * out.slot_rows >= 200

    def test_pow2_bucket_fixed_point_on_plan_exchange(self):
        ctx = _ctx(slot=128, maxes=(100, 5))
        plan = plan_exchange(ctx.round_max_rows, 128, 32)
        assert pass_pow2_bucket(plan, ctx) is plan

    def test_coalesce_collapses_even_chunks(self):
        """4 chunks of 16 covering 60 rows: same 64 staged rows as 2x32 or
        1x64, so coalescing walks all the way up to one launch."""
        ctx = _ctx(slot=64, maxes=(60,))
        plan = plan_exchange(ctx.round_max_rows, 64, 16)
        assert plan.chunks_per_round == (4,)
        out = pass_coalesce_chunks(plan, ctx)
        assert (out.slot_rows, out.chunks_per_round) == (64, (1,))
        assert out.staged_rows(N_EXEC) == plan.staged_rows(N_EXEC)

    def test_coalesce_keeps_odd_chunks(self):
        """3 chunks of 16 covering 48 rows: doubling to 2x32 would stage 64
        rows (more padding), so the smaller slot is kept."""
        ctx = _ctx(slot=64, maxes=(48,))
        plan = plan_exchange(ctx.round_max_rows, 64, 16)
        assert plan.chunks_per_round == (3,)
        out = pass_coalesce_chunks(plan, ctx)
        assert (out.slot_rows, out.chunks_per_round) == (16, (3,))

    def test_coalesce_skips_single_shot(self):
        plan = ExchangePlan(slot_rows=16, chunks_per_round=(1,), single_shot=True)
        assert pass_coalesce_chunks(plan, _ctx(slot=16, maxes=(16,))) is plan

    def test_reorder_ascending_footprint(self):
        plan = ExchangePlan(slot_rows=16, chunks_per_round=(3, 1, 2))
        out = pass_reorder_rounds(plan, _ctx(maxes=(48, 16, 32)))
        assert out.round_order == (1, 2, 0)
        # whole rounds move as units; chunk order within a round is kept
        assert out.ordered_subrounds() == [
            (1, 0, 1),
            (2, 0, 2),
            (2, 1, 2),
            (0, 0, 3),
            (0, 1, 3),
            (0, 2, 3),
        ]

    def test_reorder_natural_order_untouched(self):
        plan = ExchangePlan(slot_rows=16, chunks_per_round=(1, 2))
        out = pass_reorder_rounds(plan, _ctx(maxes=(16, 32)))
        assert out.round_order == ()

    def test_bad_round_order_rejected(self):
        plan = ExchangePlan(
            slot_rows=16, chunks_per_round=(1, 1), round_order=(0, 0)
        )
        with pytest.raises(ValueError, match="permutation"):
            plan.ordered_subrounds()

    def test_optimize_plan_preserves_coverage(self, rng):
        """Property gate over the whole pipeline: after every pass, each
        round's chunks x slot still covers that round's hottest lane."""
        for _ in range(25):
            nrounds = int(rng.integers(1, 5))
            maxes = tuple(int(m) for m in rng.integers(0, 500, size=nrounds))
            slot = int(rng.integers(1, 400))
            quota = int(rng.integers(1, 400))
            ctx = _ctx(slot=slot, maxes=maxes)
            plan = plan_exchange(maxes, slot, quota)
            out = optimize_plan(plan, ctx)
            for r, m in enumerate(maxes):
                assert out.chunks_per_round[r] * out.slot_rows >= m
            # slot stays a pow2 compile bucket
            assert out.slot_rows & (out.slot_rows - 1) == 0
            if out.round_order:
                assert sorted(out.round_order) == list(range(nrounds))

    def test_default_pass_order(self):
        assert DEFAULT_PASSES == (
            pass_pow2_bucket,
            pass_coalesce_chunks,
            pass_reorder_rounds,
        )


# ----------------------------------------------------------------------
# PlanSignals: registry snapshot -> planner inputs


class _FakeRegistry:
    def __init__(self, samples):
        self._samples = samples

    def snapshot(self):
        return list(self._samples)


class TestPlanSignals:
    def test_from_registry_distills_families(self):
        drain = (("kind", "exchange.pipeline.drain"),)
        submit = (("kind", "exchange.pipeline.submit"),)
        reg = _FakeRegistry(
            [
                MetricSample("ops", "used_rows_total", 50.0, drain),
                MetricSample("ops", "padded_rows_total", 50.0, drain),
                MetricSample("ops", "total_ns_total", 2e9, drain),
                MetricSample("ops", "total_ns_total", 1e9, submit),
                MetricSample("wire", "rx_stall_p99_ns", 7e6),
                MetricSample("wire", "credit_stall_ns", 2e6),
                MetricSample("wire", "peer_health", 0.9),
                MetricSample("wire", "peer_health", 0.4),
                MetricSample("wire", "breaker_open", 1.0),
                MetricSample("compress", "raw_bytes", 100.0),
                MetricSample("compress", "encoded_bytes", 50.0),
            ]
        )
        sig = PlanSignals.from_registry(reg)
        assert sig.padding_fraction == pytest.approx(0.5)
        assert sig.drain_occupancy == pytest.approx(2.0)
        assert sig.rx_stall_p99_ns == 7_000_000
        assert sig.credit_stall_ns == 2_000_000
        assert sig.worst_peer_health == pytest.approx(0.4)  # min across peers
        assert sig.breakers_open == 1
        assert sig.compression_ratio == pytest.approx(2.0)

    def test_empty_registry_is_cold_cluster(self):
        sig = PlanSignals.from_registry(_FakeRegistry([]))
        assert sig == PlanSignals()

    def test_describe_is_flat_and_json_safe(self):
        d = PlanSignals().describe()
        assert all(isinstance(v, (int, float)) for v in d.values())


# ----------------------------------------------------------------------
# AdaptivePlanner: deterministic telemetry rules


class TestAdaptivePlannerQuota:
    # n=4 executors, hot lane 300 rows in a 4096-row slot: the single-shot
    # plan stages mostly padding, so the planner should chunk.
    def _skewed(self, **kw):
        # used ~ one hot lane per sender; padding >> default 0.5 target
        return _ctx(slot=3000, maxes=(300,), used=4 * 400, **kw)

    def test_low_padding_stays_single_shot(self):
        ctx = _ctx(slot=3000, maxes=(4000,), used=4 * 4 * 4096)
        plan = AdaptivePlanner(TpuShuffleConf()).plan(ctx)
        assert plan.single_shot is True

    def test_high_padding_picks_staged_minimizing_quota(self):
        """pow2 search over [256, 4096]: staged(256)=512, staged(512)=512,
        staged(1024)=1024 ... — ties break toward the LARGER quota (fewer
        launches for the same footprint), so 512 wins."""
        plan = AdaptivePlanner(TpuShuffleConf()).plan(self._skewed())
        assert plan.single_shot is False
        assert plan.slot_rows == 512
        assert plan.chunks_per_round == (1,)

    def test_min_quota_floor_respected(self):
        conf = TpuShuffleConf(planner_min_quota_rows=1024)
        plan = AdaptivePlanner(conf).plan(self._skewed())
        assert plan.single_shot is False
        assert plan.slot_rows == 1024

    def test_floor_above_slot_means_single_shot(self):
        """A floor past the slot leaves only q == slot in the search — the
        plan must stay single-shot (chunking cannot shrink the footprint)."""
        conf = TpuShuffleConf(planner_min_quota_rows=1 << 20)
        plan = AdaptivePlanner(conf).plan(self._skewed())
        assert plan.single_shot is True
        assert plan.slot_rows == 4096

    def test_padding_target_knob_gates_chunking(self):
        conf = TpuShuffleConf(planner_target_padding=0.99)
        plan = AdaptivePlanner(conf).plan(self._skewed())
        assert plan.single_shot is True

    def test_forced_static_quota_wins(self):
        """slotQuotaRows > 0 pins the collective schedule; the adaptive
        layer must not second-guess it (only optimize geometry-safely)."""
        conf = TpuShuffleConf(slot_quota_rows=16)
        ctx = _ctx(slot=64, maxes=(48,), used=10)
        plan = AdaptivePlanner(conf).plan(ctx)
        static = StaticPlanner(conf).plan(ctx)
        assert (plan.slot_rows, plan.chunks_per_round) == (
            static.slot_rows,
            static.chunks_per_round,
        )

    def test_lockstep_schedule_ignores_signals(self):
        """THE SPMD invariant: two hosts with the same agreed geometry but
        wildly different local telemetry derive the identical collective
        schedule (only serve-plane fields may diverge)."""
        hot = PlanSignals(
            padding_fraction=0.99,
            drain_occupancy=3.0,
            rx_stall_p99_ns=10**9,
            credit_stall_ns=10**9,
            worst_peer_health=0.0,
            breakers_open=3,
            compression_ratio=1.0,
        )
        conf = TpuShuffleConf(fetch_hedge_ms=1, fetch_hedge_max_ms=100)
        a = AdaptivePlanner(conf).plan(self._skewed())
        b = AdaptivePlanner(conf).plan(self._skewed(signals=hot))
        collective = lambda p: (
            p.slot_rows,
            p.chunks_per_round,
            p.single_shot,
            p.round_order,
            p.lowering,
        )
        assert collective(a) == collective(b)


class TestAdaptivePlannerServePlane:
    def test_hedge_stretches_on_degraded_stall_tail(self):
        conf = TpuShuffleConf(fetch_hedge_ms=5, fetch_hedge_max_ms=50)
        sig = PlanSignals(worst_peer_health=0.3, rx_stall_p99_ns=int(10e6))
        plan = AdaptivePlanner(conf).plan(_ctx(signals=sig))
        assert plan.hedge_ms == 20  # 2x the 10ms p99 stall

    def test_hedge_clamped_to_max(self):
        conf = TpuShuffleConf(fetch_hedge_ms=5, fetch_hedge_max_ms=50)
        sig = PlanSignals(breakers_open=1, rx_stall_p99_ns=int(40e6))
        plan = AdaptivePlanner(conf).plan(_ctx(signals=sig))
        assert plan.hedge_ms == 50  # 80ms ask, clamped

    def test_healthy_peers_keep_conf_hedge(self):
        conf = TpuShuffleConf(fetch_hedge_ms=5, fetch_hedge_max_ms=50)
        sig = PlanSignals(rx_stall_p99_ns=int(40e6))  # stall but healthy
        plan = AdaptivePlanner(conf).plan(_ctx(signals=sig))
        assert plan.hedge_ms == 5

    def test_incompressible_traffic_drops_codec(self):
        conf = TpuShuffleConf(wire_compress_codec="rle")
        sig = PlanSignals(compression_ratio=1.01)
        plan = AdaptivePlanner(conf).plan(_ctx(signals=sig))
        assert plan.codec == "off"

    def test_compressible_traffic_keeps_codec(self):
        conf = TpuShuffleConf(wire_compress_codec="rle")
        sig = PlanSignals(compression_ratio=2.0)
        plan = AdaptivePlanner(conf).plan(_ctx(signals=sig))
        assert plan.codec == "rle"

    def test_credit_stall_doubles_streams_capped(self):
        sig = PlanSignals(credit_stall_ns=int(5e6))
        plan = AdaptivePlanner(TpuShuffleConf(wire_streams=4)).plan(
            _ctx(signals=sig)
        )
        assert plan.streams == 8
        plan = AdaptivePlanner(TpuShuffleConf(wire_streams=8)).plan(
            _ctx(signals=sig)
        )
        assert plan.streams == 8  # cap

    def test_drain_bottleneck_deepens_pipeline_capped(self):
        sig = PlanSignals(drain_occupancy=1.5)
        plan = AdaptivePlanner(TpuShuffleConf()).plan(_ctx(signals=sig))
        assert plan.pipeline_depth == 3  # default 2 + 1
        plan = AdaptivePlanner(TpuShuffleConf(pipeline_depth=4)).plan(
            _ctx(signals=sig)
        )
        assert plan.pipeline_depth == 4  # cap


# ----------------------------------------------------------------------
# host_recv_mode gate: ONE validation, identical everywhere


class TestHostRecvModeGate:
    def test_vocabulary_pin(self):
        assert HOST_RECV_MODES == ("array", "memmap", "device")

    def test_unknown_mode_names_full_vocabulary(self):
        with pytest.raises(
            ValueError, match=r"unknown host_recv_mode 'bogus' \(array\|memmap\|device\)"
        ):
            validate_host_recv_mode("bogus")

    def test_unsupported_mode_names_deployment(self):
        with pytest.raises(
            ValueError,
            match=r"host_recv_mode 'device' is not supported by the SPMD executor",
        ):
            validate_host_recv_mode(
                "device", allowed=("array", "memmap"), where="the SPMD executor"
            )

    def test_cluster_rejects_unknown_mode_before_staging(self):
        """The loopback cluster routes through the same gate, before any
        staging allocation — the error fires on run_exchange, not mid-drain."""
        conf = _conf(0)
        cluster = TpuShuffleCluster(
            dataclasses.replace(conf, host_recv_mode="bogus"),
            num_executors=N_EXEC,
        )
        _write_skewed(cluster, 0, N_EXEC, 4)
        with pytest.raises(ValueError, match="unknown host_recv_mode"):
            cluster.run_exchange(0)


# ----------------------------------------------------------------------
# build_plan_exchange: THE lowering dispatch == the per-variant builders

_needs4 = pytest.mark.skipif(
    jax.device_count() < 4, reason="needs a 4-device mesh (conftest forces 8)"
)


@_needs4
class TestBuildPlanExchange:
    N, SLOT, LANE = 4, 8, 8

    def _mesh(self):
        from sparkucx_tpu.ops.exchange import make_mesh

        return make_mesh(self.N)

    def _case(self, rng):
        n, slot = self.N, self.SLOT
        data = rng.integers(
            -100, 100, size=(n * n * slot, self.LANE), dtype=np.int32
        )
        sizes = rng.integers(0, slot + 1, size=(n, n)).astype(np.int32)
        return data, sizes

    def _run(self, fn, mesh, data, sizes):
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P("ex", None))
        recv, rs = fn(
            jax.device_put(data, sharding), jax.device_put(sizes, sharding)
        )
        return np.asarray(recv), np.asarray(rs)

    def _plan_fn(self, mesh, impl, quantize=None):
        return build_plan_exchange(
            mesh,
            num_executors=self.N,
            send_rows=self.N * self.SLOT,
            lane=self.LANE,
            axis_name="ex",
            impl=impl,
            quantize=quantize,
        )

    def test_stock_matches_build_exchange(self, rng):
        from sparkucx_tpu.ops.exchange import ExchangeSpec, build_exchange

        mesh = self._mesh()
        data, sizes = self._case(rng)
        spec = ExchangeSpec(
            num_executors=self.N,
            send_rows=self.N * self.SLOT,
            recv_rows=self.N * self.SLOT,
            lane=self.LANE,
        )
        recv_ref, rs_ref = self._run(
            build_exchange(mesh, spec), mesh, data.copy(), sizes
        )
        recv, rs = self._run(self._plan_fn(mesh, "stock"), mesh, data.copy(), sizes)
        np.testing.assert_array_equal(rs, rs_ref)
        assert recv.tobytes() == recv_ref.tobytes()

    def test_pallas_tier_bit_identical_to_stock(self, rng):
        mesh = self._mesh()
        data, sizes = self._case(rng)
        recv_ref, rs_ref = self._run(
            self._plan_fn(mesh, "stock"), mesh, data.copy(), sizes
        )
        recv, rs = self._run(self._plan_fn(mesh, "pallas"), mesh, data.copy(), sizes)
        np.testing.assert_array_equal(rs, rs_ref)
        assert recv.tobytes() == recv_ref.tobytes()

    def test_quantized_route_matches_direct_builder(self, rng):
        from sparkucx_tpu.ops.compress import QuantizeSpec
        from sparkucx_tpu.ops.exchange import ExchangeSpec
        from sparkucx_tpu.ops.ici_exchange import build_quantized_exchange

        mesh = self._mesh()
        q = QuantizeSpec(mode="int8", block_size=8)
        data = np.random.default_rng(3).normal(
            scale=5.0, size=(self.N * self.N * self.SLOT, self.LANE)
        ).astype(np.float32)
        sizes = np.random.default_rng(4).integers(
            0, self.SLOT + 1, size=(self.N, self.N)
        ).astype(np.int32)
        spec = ExchangeSpec(
            num_executors=self.N,
            send_rows=self.N * self.SLOT,
            recv_rows=self.N * self.SLOT,
            lane=self.LANE,
        )
        recv_ref, rs_ref = self._run(
            build_quantized_exchange(mesh, spec, q),
            mesh,
            data.copy(),
            sizes,
        )
        recv, rs = self._run(
            self._plan_fn(mesh, "stock", quantize=q), mesh, data.copy(), sizes
        )
        np.testing.assert_array_equal(rs, rs_ref)
        assert recv.tobytes() == recv_ref.tobytes()


# ----------------------------------------------------------------------
# transport golden equivalence: plan-driven runs vs the default engine
# (same seeded writes as tests/test_skew.py — byte-for-byte receive state)


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


def _write_skewed(cluster, shuffle_id, M, R, seed=77):
    meta = cluster.create_shuffle(shuffle_id, M, R)
    rng = np.random.default_rng(seed)
    oracle = {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(shuffle_id, m)
        for r in range(R):
            size = int(rng.integers(2000, 3000)) if r == 0 else int(rng.integers(1, 300))
            payload = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    return meta, oracle


def _fetch_all(cluster, meta, shuffle_id, M, R, oracle):
    for r in range(R):
        consumer = meta.owner_of_reduce(r)
        t = cluster.transport(consumer)
        bufs = [_buf(8192) for _ in range(M)]
        reqs = t.fetch_blocks_by_block_ids(
            consumer,
            [ShuffleBlockId(shuffle_id, m, r) for m in range(M)],
            bufs,
            [None] * M,
        )
        for m in range(M):
            res = reqs[m].wait(5)
            assert res.status == OperationStatus.SUCCESS, str(res.error)
            assert bufs[m].host_view()[: bufs[m].size].tobytes() == oracle[(m, r)]


def _conf(quota, mode="array", **kw):
    return TpuShuffleConf(
        staging_capacity_per_executor=N_EXEC * 4096,
        block_alignment=128,
        num_executors=N_EXEC,
        host_recv_mode=mode,
        slot_quota_rows=quota,
        **kw,
    )


def _exchange(conf, M=3 * N_EXEC, R=8):
    cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
    meta, oracle = _write_skewed(cluster, 0, M, R)
    cluster.run_exchange(0)
    return cluster, meta, oracle


def _assert_prefix_equal(meta, base_meta):
    """Every consumer's shard is byte-equal to the default run's receive
    buffer over the valid prefix (tight chunked shards vs padded single-shot
    shards — same bytes where it matters)."""
    assert len(meta.recv_sizes) == len(base_meta.recv_sizes)
    for rnd in range(len(base_meta.recv_sizes)):
        np.testing.assert_array_equal(
            meta.recv_sizes[rnd], base_meta.recv_sizes[rnd]
        )
        for j in range(N_EXEC):
            used = int(base_meta.recv_sizes[rnd][j].sum()) * 128
            got = bytes(meta.recv_shards[rnd][j][: max(used, 0)].reshape(-1))
            want = bytes(base_meta.recv_shards[rnd][j][:used])
            assert got == want


class TestClusterGoldenEquivalence:
    def test_optimize_on_single_shot_bit_identical(self):
        base_cluster, base_meta, oracle = _exchange(_conf(0))
        cluster, meta, _ = _exchange(_conf(0, planner_optimize=True))
        _assert_prefix_equal(meta, base_meta)
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    def test_optimize_on_quota_bit_identical(self):
        """The reorder pass permutes sub-round SUBMISSION on the quota path;
        results must still land in natural round order, byte-identical."""
        base_cluster, base_meta, oracle = _exchange(_conf(8))
        cluster, meta, _ = _exchange(_conf(8, planner_optimize=True))
        assert len(base_meta.recv_sizes) > 1, "should spill multiple rounds"
        for rnd in range(len(base_meta.recv_sizes)):
            np.testing.assert_array_equal(
                meta.recv_sizes[rnd], base_meta.recv_sizes[rnd]
            )
            for j in range(N_EXEC):
                assert bytes(meta.recv_shards[rnd][j]) == bytes(
                    base_meta.recv_shards[rnd][j]
                )
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    @pytest.mark.parametrize("mode", ["array", "memmap", "device"])
    def test_adaptive_bit_identical_each_recv_mode(self, mode, tmp_path):
        """The adaptive planner re-plans from geometry (no telemetry yet on
        a fresh cluster) and chunks the padded skew away — the bytes served
        to every consumer must not move, in any host_recv_mode."""
        base_cluster, base_meta, oracle = _exchange(_conf(0))
        kw = {"planner_mode": "adaptive", "planner_min_quota_rows": 8}
        if mode == "memmap":
            kw["spill_dir"] = str(tmp_path)
        if mode == "device":
            kw["keep_device_recv"] = True
        cluster, meta, _ = _exchange(_conf(0, mode=mode, **kw))
        if mode == "device":
            assert meta.recv_shards is None  # no host copy, fetch from HBM
        else:
            _assert_prefix_equal(meta, base_meta)
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    def test_adaptive_actually_chunked(self):
        """Guard against the adaptive path silently degenerating into the
        static single-shot plan: on this skew (hot lane ~24 rows, mostly
        1-3 row lanes in a 32-row slot) predicted padding clears the 0.5
        target and the quota search must fire — visible as tight shards and
        drain-side padding telemetry."""
        cluster, meta, _ = _exchange(
            _conf(0, planner_mode="adaptive", planner_min_quota_rows=8)
        )
        tight = [
            meta.recv_shards[rnd][j].nbytes
            == int(meta.recv_sizes[rnd][j].sum()) * 128
            for rnd in range(len(meta.recv_sizes))
            for j in range(N_EXEC)
        ]
        assert all(tight), "adaptive plan should drain tight chunked shards"
        drain = cluster.stats.summary("exchange.pipeline.drain")
        assert drain.used_rows > 0

    def test_pallas_lowering_bit_identical(self):
        base_cluster, base_meta, oracle = _exchange(_conf(0))
        cluster, meta, _ = _exchange(_conf(0, exchange_impl="pallas"))
        _assert_prefix_equal(meta, base_meta)
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    def test_pallas_quota_bit_identical(self):
        base_cluster, base_meta, oracle = _exchange(_conf(8))
        cluster, meta, _ = _exchange(_conf(8, exchange_impl="pallas"))
        for rnd in range(len(base_meta.recv_sizes)):
            np.testing.assert_array_equal(
                meta.recv_sizes[rnd], base_meta.recv_sizes[rnd]
            )
            for j in range(N_EXEC):
                assert bytes(meta.recv_shards[rnd][j]) == bytes(
                    base_meta.recv_shards[rnd][j]
                )
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    def test_compressed_wire_adaptive_serves_exact_bytes(self):
        """Serve-plane codec under an adaptive plan: pages ride the wire
        RLE-encoded, consumers still read the exact oracle bytes."""
        cluster, meta, oracle = _exchange(
            _conf(
                0,
                planner_mode="adaptive",
                planner_min_quota_rows=8,
                wire_compress_codec="rle",
            )
        )
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    def test_quantized_conf_rides_plan(self):
        """Quantization knobs land on the plan (serve/aggregation plane);
        the collective executor never quantizes shuffle bytes — fetches
        still serve the exact oracle."""
        cluster, meta, oracle = _exchange(
            _conf(8, quantize_mode="int8", quantize_block_size=64)
        )
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)


class TestExchangePlanSpan:
    def test_plan_traced_per_shuffle(self):
        """Every exchange emits one ``exchange.plan`` instant carrying the
        full plan describe() plus the signal snapshot it was justified by."""
        prev_enabled, prev_recording = TRACER.enabled, TRACER.recording
        TRACER.clear()
        TRACER.enable()
        try:
            _exchange(_conf(0, planner_mode="adaptive", planner_min_quota_rows=8))
            evs = [e for e in TRACER.events if e["name"] == "exchange.plan"]
            assert evs, "exchange.plan instant missing"
            args = evs[0]["args"]
            assert args["planner"] == "AdaptivePlanner"
            assert args["shuffle_id"] == 0
            for key in (
                "slot_rows",
                "chunks_per_round",
                "single_shot",
                "lowering",
                "codec",
                "hedge_ms",
                "signal_padding_fraction",
                "signal_worst_peer_health",
                "signal_compression_ratio",
            ):
                assert key in args, key
        finally:
            TRACER.enabled, TRACER.recording = prev_enabled, prev_recording
            TRACER.clear()
