"""Chaos tests: fault-injection harness, neighbor replication, reducer failover.

Pins the PR's robustness contracts:

* the harness itself (arm/match/times/reset, factories, telemetry),
* seal -> background REPLICA_PUT push to ring neighbors -> replica tier
  accounting on both ends (``replication.factor``; factor=0 pushes nothing),
* replica serving: ``read_block`` and the peer wire serve a replicated block
  when the primary copy is gone,
* the headline chaos scenario: kill one loopback executor mid-superstep and
  the reducer's output is BIT-IDENTICAL to the no-fault run, with bounded
  stall telemetry and failovers accounted.
"""

import time

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import (
    BlockNotFoundError,
    OperationStatus,
    TransportError,
)
from sparkucx_tpu.shuffle.reader import TpuShuffleReader
from sparkucx_tpu.shuffle.resolver import ring_neighbors
from sparkucx_tpu.testing import faults
from sparkucx_tpu.transport.peer import PeerTransport


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


def _cluster(n, **conf_kw):
    conf_kw.setdefault("staging_capacity_per_executor", 1 << 20)
    conf = TpuShuffleConf(**conf_kw)
    ts = [PeerTransport(conf, executor_id=i) for i in range(n)]
    addrs = [t.init() for t in ts]
    for t in ts:
        for j, a in enumerate(addrs):
            if j != t.executor_id:
                t.add_executor(j, a)
    return ts


def _close_all(ts):
    for t in ts:
        t.close()


# ---------------------------------------------------------------------------
# the harness itself
# ---------------------------------------------------------------------------


class TestHarness:
    def test_disarmed_is_noop(self):
        faults.check("nowhere", peer="x")
        assert faults.transform("nowhere", b"abc") == b"abc"
        assert not faults.active

    def test_times_and_match(self):
        hits = []
        faults.arm("p", lambda **ctx: hits.append(ctx), times=2, match={"lane": 1})
        faults.check("p", lane=0)  # match miss
        faults.check("q", lane=1)  # point miss
        for _ in range(5):
            faults.check("p", lane=1)
        assert len(hits) == 2  # times bound respected
        assert faults.fired["p"] == 2

    def test_sever_and_fail_raise(self):
        faults.arm("p", faults.sever("boom"))
        with pytest.raises(ConnectionResetError, match="boom"):
            faults.check("p")
        faults.reset()
        faults.arm("p", faults.fail(ValueError("typed")))
        with pytest.raises(ValueError, match="typed"):
            faults.check("p")

    def test_stall_sleeps(self):
        faults.arm("p", faults.stall(0.05))
        t0 = time.monotonic()
        faults.check("p")
        assert time.monotonic() - t0 >= 0.04

    def test_garble_transform_roundtrip(self):
        faults.arm("p", faults.garble(0xFF))
        out = faults.transform("p", b"\x00\x0f\xf0")
        assert bytes(out) == b"\xff\xf0\x0f"

    def test_context_manager_resets_on_error(self):
        with pytest.raises(RuntimeError):
            with faults.injected_faults(("p", faults.sever())):
                assert faults.active
                raise RuntimeError("test body explodes")
        assert not faults.active and not faults.fired

    def test_disarm_single_entry(self):
        e1 = faults.arm("p", faults.stall(0))
        faults.arm("q", faults.stall(0))
        faults.disarm(e1)
        assert faults.active  # q still armed
        faults.check("p")
        assert "p" not in faults.fired


# ---------------------------------------------------------------------------
# neighbor replication (seal -> REPLICA_PUT -> replica tier)
# ---------------------------------------------------------------------------


def _stage(t, shuffle_id, num_mappers, num_reducers, seed=0):
    """Stage deterministic random blocks on executor ``t``; returns
    {(map, reduce): payload}."""
    rng = np.random.default_rng(seed)
    t.store.create_shuffle(shuffle_id, num_mappers, num_reducers)
    payloads = {}
    for m in range(num_mappers):
        w = t.store.map_writer(shuffle_id, m)
        for r in range(num_reducers):
            data = rng.integers(0, 256, size=200 + 37 * (m + r), dtype=np.uint8).tobytes()
            payloads[(m, r)] = data
            w.write_partition(r, data)
        w.commit()
    return payloads


class TestReplication:
    def test_seal_replicates_to_ring_neighbor(self):
        ts = _cluster(2, replication_factor=1)
        try:
            payloads = _stage(ts[0], 7, 2, 3)
            ts[0].store.seal(7)
            assert ts[0].replication_wait(7, timeout=10.0)
            stats = ts[1].store.replica_stats()
            assert stats["replica_sources"] == 1
            assert stats["replica_bytes"] == sum(len(p) for p in payloads.values())
            for (m, r), data in payloads.items():
                view = ts[1].store.replica_view(7, m, r)
                assert view is not None
                arr, off, ln = view
                assert arr[off : off + ln].tobytes() == data
            assert ts[0].replica_stats["acks"] == ts[0].replica_stats["pushed_rounds"] > 0
        finally:
            _close_all(ts)

    def test_factor_zero_pushes_nothing(self):
        ts = _cluster(2, replication_factor=0)
        try:
            _stage(ts[0], 7, 1, 2)
            ts[0].store.seal(7)
            assert ts[0].replication_wait(7, timeout=0.5)  # nothing pending
            assert ts[0].replica_stats["pushed_rounds"] == 0
            assert ts[1].store.replica_stats()["replica_sources"] == 0
        finally:
            _close_all(ts)

    def test_replica_serves_read_block_and_wire(self):
        """A block the holder never staged is served from its replica tier —
        both through read_block (BlockNotFoundError otherwise) and over the
        peer wire (_resolve_one's replica arm)."""
        ts = _cluster(2, replication_factor=1)
        try:
            payloads = _stage(ts[0], 3, 1, 2)
            ts[0].store.seal(3)
            assert ts[0].replication_wait(3, timeout=10.0)
            # executor 1 never created shuffle 3 locally; replica serves anyway
            got = ts[1].store.read_block(3, 0, 1)
            assert got == payloads[(0, 1)]
            # and over the wire: executor 0 fetches its own block BACK from 1
            buf = _buf(len(payloads[(0, 0)]))
            req = ts[0].fetch_block(1, 3, 0, 0, buf)
            deadline = time.monotonic() + 5
            while not req.completed() and time.monotonic() < deadline:
                ts[0].progress()
            res = req.wait(1)
            assert res.status == OperationStatus.SUCCESS, str(res.error)
            assert buf.host_view()[: buf.size].tobytes() == payloads[(0, 0)]
        finally:
            _close_all(ts)

    def test_delayed_replication_wait_blocks_until_settled(self):
        ts = _cluster(2, replication_factor=1)
        try:
            faults.arm("replica.push", faults.delay(0.3), times=1)
            _stage(ts[0], 4, 1, 1)
            ts[0].store.seal(4)
            assert not ts[0].replication_wait(4, timeout=0.05)  # still delayed
            assert ts[0].replication_wait(4, timeout=10.0)
            assert ts[1].store.replica_view(4, 0, 0) is not None
        finally:
            _close_all(ts)

    def test_apply_sever_counts_as_unsettled(self):
        """Severing the receiving server mid-apply loses the ack; the pusher's
        replication_wait reports unsettled instead of hanging forever."""
        ts = _cluster(2, replication_factor=1)
        try:
            faults.arm("replica.apply", faults.sever(), times=1)
            _stage(ts[0], 5, 1, 1)
            ts[0].store.seal(5)
            assert not ts[0].replication_wait(5, timeout=0.7)
            assert ts[1].store.replica_view(5, 0, 0) is None
        finally:
            _close_all(ts)

    def test_ring_neighbors_placement(self):
        assert ring_neighbors(1, [0, 1, 2], 1) == [2]
        assert ring_neighbors(2, [0, 1, 2], 1) == [0]
        assert ring_neighbors(1, [0, 1, 2], 2) == [2, 0]
        assert ring_neighbors(1, [0, 1, 2], 99) == [2, 0]  # capped at ring-1
        assert ring_neighbors(5, [0, 1, 2], 1) == []  # not a member
        assert ring_neighbors(0, [0], 1) == []  # alone
        assert ring_neighbors(0, [0, 1], 0) == []  # disabled

    def test_block_not_found_is_typed_and_addressed(self):
        ts = _cluster(1, replication_factor=0)
        try:
            ts[0].store.create_shuffle(9, 1, 1)
            with pytest.raises(BlockNotFoundError) as ei:
                ts[0].store.read_block(9, 0, 0)
            assert (ei.value.shuffle_id, ei.value.map_id, ei.value.reduce_id) == (9, 0, 0)
            assert isinstance(ei.value, TransportError)  # old catch-sites work
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# the headline chaos scenario: executor killed mid-superstep
# ---------------------------------------------------------------------------


def _reader(transport, payloads, num_mappers, num_reducers, executors, **kw):
    kw.setdefault("fetch_retries", 2)
    kw.setdefault("fetch_deadline_ms", 2000)
    kw.setdefault("fetch_backoff_ms", 10)
    return TpuShuffleReader(
        transport,
        executor_id=transport.executor_id,
        shuffle_id=0,
        start_partition=0,
        end_partition=num_reducers,
        num_mappers=num_mappers,
        block_sizes=lambda m, r: len(payloads[(m, r)]),
        max_blocks_per_request=1,  # one window per block: kill lands mid-stream
        sender_of=lambda m: 1,
        replica_of=lambda primary: ring_neighbors(primary, executors, 1),
        **kw,
    )


class TestExecutorLossChaos:
    def _run(self, kill: bool):
        """Stage on executor 1 (replica -> executor 2), read from executor 0;
        with ``kill``, executor 1 dies after the first block is consumed."""
        ts = _cluster(3, replication_factor=1, wire_timeout_ms=5000)
        try:
            payloads = _stage(ts[1], 0, 2, 3, seed=42)
            ts[1].store.seal(0)
            assert ts[1].replication_wait(0, timeout=10.0)
            reader = _reader(ts[0], payloads, 2, 3, executors=[0, 1, 2])
            got = {}
            it = reader.fetch_blocks()
            first = next(it)
            got[(first.block_id.map_id, first.block_id.reduce_id)] = bytes(first.data)
            first.release()
            if kill:
                faults.kill_executor(ts[1])  # SIGKILL stand-in, mid-traffic
            for blk in it:
                got[(blk.block_id.map_id, blk.block_id.reduce_id)] = bytes(blk.data)
                blk.release()
            return got, reader.metrics
        finally:
            _close_all(ts)

    def test_kill_mid_superstep_bit_identical(self):
        baseline, base_metrics = self._run(kill=False)
        chaotic, metrics = self._run(kill=True)
        assert chaotic == baseline  # bit-identical output despite the kill
        assert base_metrics.failovers == 0
        assert metrics.failovers >= 1  # replicas actually served
        assert metrics.blocks_retried >= 1
        # bounded stall: the dead peer fails fast (reset) or at the deadline,
        # never an unbounded spin — generous CI bound, far below hang territory
        assert metrics.fetch_wait_ns < 30 * 10**9

    def test_all_executors_dead_raises_typed(self):
        """When primary AND replica are gone the reader raises a TransportError
        naming every candidate — no silent truncation of the stream."""
        ts = _cluster(3, replication_factor=1, wire_timeout_ms=2000)
        try:
            payloads = _stage(ts[1], 0, 1, 1, seed=7)
            ts[1].store.seal(0)
            assert ts[1].replication_wait(0, timeout=10.0)
            faults.kill_executor(ts[1])
            faults.kill_executor(ts[2])
            reader = _reader(
                ts[0], payloads, 1, 1, executors=[0, 1, 2],
                fetch_retries=1, fetch_deadline_ms=500,
            )
            with pytest.raises(TransportError, match=r"across executors \[1, 2\]"):
                list(reader.fetch_blocks())
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# tiered eviction x replication: demoted rounds through the chaos path
# ---------------------------------------------------------------------------


class TestDemotedRoundReplication:
    def test_demoted_round_bit_identical_through_kill(self):
        """Eviction composed with the existing resilience features: the
        primary's sealed round is demoted to disk (checksummed + compressed
        striped wire), the first fetch restages it transparently, the primary
        is then killed mid-stream and the ring replica — never demoted —
        serves the remainder.  Output must be bit-identical throughout."""
        from sparkucx_tpu.service.eviction import EvictionManager

        ts = _cluster(
            3,
            replication_factor=1,
            wire_timeout_ms=5000,
            wire_streams=2,
            wire_checksum=True,
            wire_compress_codec="dict",
        )
        try:
            payloads = _stage(ts[1], 0, 2, 3, seed=9)
            ts[1].store.seal(0)
            assert ts[1].replication_wait(0, timeout=10.0)
            ev = EvictionManager(ts[1].store)
            ts[1].store.eviction = ev
            while ts[1].store.round_tier(0, 0) != "disk":
                assert ts[1].store.demote_round(0, 0) is not None
            reader = _reader(ts[0], payloads, 2, 3, executors=[0, 1, 2])
            got = {}
            it = reader.fetch_blocks()
            first = next(it)  # cold fetch: restages the demoted round
            got[(first.block_id.map_id, first.block_id.reduce_id)] = bytes(first.data)
            first.release()
            assert ts[1].store.round_tier(0, 0) == "host"
            assert ev.eviction_stats()["restages"] >= 1
            faults.kill_executor(ts[1])  # replica takes over mid-stream
            for blk in it:
                got[(blk.block_id.map_id, blk.block_id.reduce_id)] = bytes(blk.data)
                blk.release()
            assert got == payloads  # bit-identical across tier + holder moves
            assert reader.metrics.failovers >= 1
        finally:
            _close_all(ts)

    def test_demotion_never_touches_replica_tier(self):
        """Demoting the primary's round is local: the neighbor's replica
        bytes stay resident and serve reads unchanged."""
        from sparkucx_tpu.service.eviction import EvictionManager

        ts = _cluster(2, replication_factor=1)
        try:
            payloads = _stage(ts[0], 6, 1, 2, seed=5)
            ts[0].store.seal(6)
            assert ts[0].replication_wait(6, timeout=10.0)
            ts[0].store.eviction = EvictionManager(ts[0].store)
            while ts[0].store.round_tier(6, 0) != "disk":
                assert ts[0].store.demote_round(6, 0) is not None
            for (m, r), data in payloads.items():
                assert ts[1].store.read_block(6, m, r) == data
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# gray-failure fault factories + chaos-kill postmortems
# ---------------------------------------------------------------------------


class TestGrayFactories:
    def test_garble_matches_per_byte_xor(self):
        """The vectorized garble must corrupt EXACTLY like the per-byte XOR it
        replaced — chaos tests pin corrupted-frame bytes, so the fast path
        cannot drift from the reference semantics."""
        rng = np.random.default_rng(123)
        data = rng.integers(0, 256, size=1 << 16, dtype=np.uint8).tobytes()
        faults.arm("p", faults.garble(0x5A))
        out = bytes(faults.transform("p", data))
        assert out == bytes(b ^ 0x5A for b in data)

    def test_throttle_paces_and_preserves_bytes(self):
        faults.arm("p", faults.throttle(10_000))  # 10 kB/s
        data = b"z" * 1000  # ~0.1 s at the armed rate
        t0 = time.monotonic()
        out = faults.transform("p", data)
        assert time.monotonic() - t0 >= 0.08  # paced...
        assert bytes(out) == data  # ...but every byte still bit-identical

    def test_flaky_is_seed_deterministic(self):
        def pattern(seed):
            act = faults.flaky(0.5, seed=seed)
            hits = []
            for _ in range(64):
                try:
                    act()
                    hits.append(False)
                except ConnectionResetError:
                    hits.append(True)
            return hits

        assert pattern(7) == pattern(7)  # same seed replays the same failures
        assert any(pattern(7)) and not all(pattern(7))
        assert pattern(7) != pattern(8)

    def test_kill_executor_idempotent_with_health_postmortem(self):
        """kill_executor captures the dying executor's peer-health/breaker
        view into its postmortem bundle BEFORE the kill, and a second kill of
        the same transport is a no-op (real processes die once)."""
        ts = _cluster(2)
        try:
            ts[1].record_peer_failure(0, "synthetic pre-kill failure")
            faults.kill_executor(ts[1])
            pm = ts[1].recorder.last_postmortem
            assert pm is not None and pm["reason"] == "chaos_kill"
            assert pm["context"]["executor"] == 1
            assert "failures" in pm["context"]["peer_health"]
            seq = pm["seq"]
            faults.kill_executor(ts[1])  # idempotent: no second bundle
            assert ts[1].recorder.last_postmortem["seq"] == seq
        finally:
            _close_all(ts)
