"""Every example in examples/ must run green, as a real subprocess.

The examples are the user-facing walkthroughs (examples/README.md); running
them end-to-end keeps the documented surface honest the same way the
integration gate keeps the daemon protocol honest."""

import os
import pathlib
import subprocess
import sys

import pytest

EXAMPLES_DIR = pathlib.Path(__file__).resolve().parent.parent / "examples"
SCRIPTS = sorted(p.name for p in EXAMPLES_DIR.glob("*.py"))


def test_examples_dir_has_scripts():
    assert len(SCRIPTS) >= 4


def test_readme_lists_every_script():
    readme = (EXAMPLES_DIR / "README.md").read_text()
    for script in SCRIPTS:
        assert script in readme, f"examples/README.md does not mention {script}"


@pytest.mark.parametrize("script", SCRIPTS)
def test_example_runs(script):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    env["PYTHONPATH"] = os.pathsep.join(
        [str(EXAMPLES_DIR.parent)] + env.get("PYTHONPATH", "").split(os.pathsep)
    ).rstrip(os.pathsep)
    r = subprocess.run(
        [sys.executable, str(EXAMPLES_DIR / script)],
        capture_output=True,
        text=True,
        timeout=240,
        env=env,
    )
    assert r.returncode == 0, f"{script} failed:\n{r.stdout}\n{r.stderr}"
    assert "OK" in r.stdout, f"{script} printed no OK checkpoint:\n{r.stdout}"
