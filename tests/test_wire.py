"""Striped zero-copy wire path tests (PR 5).

Pins the four contracts the striped transport adds on top of the peer wire:

* **streams=1 bit-equality** — with one lane, the bytes on the wire are
  EXACTLY the pre-striping frame format (golden-byte pin, both directions),
  and AM ids 5/6 never appear.
* **chunk-frame oracle** — a striped fetch (streams=2/4) returns byte-for-byte
  what the single-frame path returns, including failures and empty blocks.
* **stripe reassembly** — chunks are self-addressing, so ANY interleaving
  across lanes (including manifest-first, manifest-last, shuffled chunks)
  reassembles correctly and completes exactly once.
* **credit accounting** — the CreditGate never admits past its budget (except
  the documented oversized-alone case), drains to zero, and the reader's
  credit-pipelined fetch yields the same stream as the serial loop.

Plus the zero-copy primitives under adversity: short reads, partial vectored
sends, and the sanitizer-enabled pooled-rx release contract.
"""

import random
import socket
import struct
import threading
import time

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import BytesBlock, MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.definitions import (
    FRAME_HEADER_SIZE,
    AmId,
    pack_chunk_hdr,
    pack_frame,
    pack_frame_prefix,
    pack_wire_hello,
    unpack_chunk_hdr,
    unpack_frame_header,
    unpack_wire_hello,
)
from sparkucx_tpu.core.operation import OperationStats, OperationStatus, Request
from sparkucx_tpu.memory.pool import MemoryPool
from sparkucx_tpu.memory.sanitizer import SanitizerError
from sparkucx_tpu.shuffle.reader import TpuShuffleReader
from sparkucx_tpu.transport.peer import (
    BlockServer,
    PeerTransport,
    _StripeRx,
    pack_batch_fetch_req,
    recv_exact,
    recv_frame,
)
from sparkucx_tpu.transport.pipeline import CreditGate

_TAG = struct.Struct("<Q")
_COUNT = struct.Struct("<I")
_SIZE = struct.Struct("<q")


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


def _drive(t, reqs, timeout=10.0):
    deadline = time.monotonic() + timeout
    while not all(r.completed() for r in reqs):
        t.progress()
        if time.monotonic() > deadline:
            raise TimeoutError("requests did not complete")
        time.sleep(0.001)


def _pair(streams=1, chunk_bytes=1 << 20, **kw):
    conf = TpuShuffleConf(wire_streams=streams, wire_chunk_bytes=chunk_bytes, **kw)
    a = PeerTransport(conf, executor_id=1)
    b = PeerTransport(conf, executor_id=2)
    a.init()
    a.add_executor(2, b.init())
    return a, b


# ---------------------------------------------------------------------------
# fake sockets for adversity injection
# ---------------------------------------------------------------------------


class ShortReadSock:
    """recv_into hands out at most ``step`` bytes per call (short reads)."""

    def __init__(self, data: bytes, step: int = 3):
        self.data = memoryview(bytes(data))
        self.pos = 0
        self.step = step

    def recv_into(self, mv, n):
        n = min(n, self.step, len(self.data) - self.pos)
        if n <= 0:
            return 0  # EOF
        mv[:n] = self.data[self.pos : self.pos + n]
        self.pos += n
        return n


class PartialSendSock:
    """sendmsg/sendall accept at most ``step`` bytes per call, splitting
    mid-iovec; everything sent accumulates in ``out``."""

    def __init__(self, step: int = 5):
        self.out = bytearray()
        self.step = step

    def sendmsg(self, bufs):
        budget = self.step
        sent = 0
        for b in bufs:
            n = min(budget - sent, b.nbytes)
            self.out += bytes(b[:n])
            sent += n
            if sent >= budget:
                break
        return sent

    def sendall(self, data):
        self.out += bytes(data)


# ---------------------------------------------------------------------------
# zero-copy receive / vectored send primitives
# ---------------------------------------------------------------------------


class TestRecvExact:
    def test_short_reads_reassemble(self):
        payload = bytes(range(256)) * 7
        got = recv_exact(ShortReadSock(payload, step=3), len(payload))
        assert got is not None and bytes(got) == payload

    def test_eof_mid_read_returns_none(self):
        assert recv_exact(ShortReadSock(b"abc", step=2), 10) is None

    def test_zero_length(self):
        got = recv_exact(ShortReadSock(b"", step=1), 0)
        assert got is not None and bytes(got) == b""

    def test_result_is_bytes_compatible(self):
        """bytearray results must work everywhere bytes did."""
        got = recv_exact(ShortReadSock(_TAG.pack(42) + b"xy", step=2), 10)
        assert _TAG.unpack_from(got)[0] == 42
        assert np.frombuffer(got, dtype=np.uint8).shape == (10,)
        assert (b"prefix" + got).endswith(b"xy")

    def test_recv_frame_over_short_reads(self):
        frame = pack_frame(AmId.MAPPER_INFO, b"hdr", b"body-bytes")
        am_id, header, body = recv_frame(ShortReadSock(frame, step=4))
        assert am_id == AmId.MAPPER_INFO
        assert bytes(header) == b"hdr" and bytes(body) == b"body-bytes"


class TestSendmsgAll:
    def test_partial_sends_preserve_stream(self):
        parts = [memoryview(bytes([i]) * (10 + i)) for i in range(7)]
        sock = PartialSendSock(step=5)
        BlockServer._sendmsg_all(sock, list(parts))
        assert bytes(sock.out) == b"".join(bytes(p) for p in parts)

    def test_iov_window_beyond_1024(self):
        parts = [b"a"] * 1500 + [b"bc"]
        sock = PartialSendSock(step=64)
        BlockServer._sendmsg_all(sock, parts)
        assert bytes(sock.out) == b"a" * 1500 + b"bc"


# ---------------------------------------------------------------------------
# chunk-frame protocol
# ---------------------------------------------------------------------------


class TestChunkProtocol:
    def test_chunk_header_roundtrip(self):
        hdr = pack_chunk_hdr(2**40, 7, 123, 2**33 + 5)
        assert unpack_chunk_hdr(hdr) == (2**40, 7, 123, 2**33 + 5)

    def test_hello_roundtrip(self):
        hdr = pack_wire_hello(2**63 + 1, 3, 4, 1 << 20)
        assert unpack_wire_hello(hdr) == (2**63 + 1, 3, 4, 1 << 20)

    def test_am_ids_pinned(self):
        # wire constants: renumbering is a protocol break
        assert int(AmId.FETCH_BLOCK_CHUNK) == 5
        assert int(AmId.WIRE_HELLO) == 6
        assert int(AmId.REPLICA_PUT) == 7
        assert int(AmId.REPLICA_ACK) == 8
        assert int(AmId.MEMBER_SUSPECT) == 9
        assert int(AmId.MEMBER_REJOIN) == 10

    def test_member_event_roundtrip(self):
        from sparkucx_tpu.core.definitions import (
            pack_member_event,
            unpack_member_event,
        )

        hdr = pack_member_event(2**40, 7, 3)
        assert unpack_member_event(hdr) == (2**40, 7, 3)


# ---------------------------------------------------------------------------
# streams=1 bit-equality pin (raw golden bytes on a real socket)
# ---------------------------------------------------------------------------


class TestSingleLaneBitEquality:
    def test_fetch_reply_bytes_pinned(self):
        """A streams=1 fetch reply must be EXACTLY the pre-striping frame:
        one FETCH_BLOCK_REQ_ACK, header=[tag, count, sizes], body=concat —
        no chunk frames, no manifest split."""
        payloads = [b"alpha-block", b"", b"g" * 4097]
        srv = BlockServer(TpuShuffleConf())
        lookup = {}
        for i, p in enumerate(payloads):
            lookup[ShuffleBlockId(9, i, 0)] = BytesBlock(p)
        srv.registry_lookup = lookup.get
        try:
            sock = socket.create_connection(srv.address, timeout=10)
            bids = list(lookup)
            req = pack_frame(AmId.FETCH_BLOCK_REQ, pack_batch_fetch_req(77, bids))
            sock.sendall(req)
            hdr = recv_exact(sock, FRAME_HEADER_SIZE)
            am_id, hlen, blen = unpack_frame_header(hdr)
            header = recv_exact(sock, hlen)
            body = recv_exact(sock, blen)
            # golden reply, constructed by hand from the documented layout
            golden_hdr = (
                _TAG.pack(77)
                + _COUNT.pack(3)
                + b"".join(_SIZE.pack(len(p)) for p in payloads)
            )
            assert am_id == AmId.FETCH_BLOCK_REQ_ACK
            assert bytes(header) == golden_hdr
            assert bytes(body) == b"".join(payloads)
            sock.close()
        finally:
            srv.close()

    def test_request_bytes_pinned(self):
        """The client request frame layout is pinned byte-for-byte."""
        bids = [ShuffleBlockId(1, 2, 3), ShuffleBlockId(4, 5, 6)]
        golden = (
            struct.pack("<IQQ", 3, 4 + 8 + 2 * 12, 0)
            + _TAG.pack(9)
            + _COUNT.pack(2)
            + struct.pack("<iii", 1, 2, 3)
            + struct.pack("<iii", 4, 5, 6)
        )
        assert pack_frame(AmId.FETCH_BLOCK_REQ, pack_batch_fetch_req(9, bids)) == golden

    def test_single_lane_emits_no_stripe_ams(self):
        """With wire.streams=1 the client opens a plain connection: no
        WIRE_HELLO handshake, so the server never forms a stripe group."""
        a, b = _pair(streams=1)
        try:
            bid = ShuffleBlockId(0, 0, 0)
            b.register(bid, BytesBlock(b"plain"))
            buf = _buf(16)
            reqs = a.fetch_blocks_by_block_ids(2, [bid], [buf], [None])
            _drive(a, reqs)
            assert reqs[0].wait(0).status == OperationStatus.SUCCESS
            assert b.server._groups == {}  # no hello ever arrived
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# striped fetch: oracle vs single-frame path
# ---------------------------------------------------------------------------


def _fetch_all(streams, payloads, chunk_bytes=64 << 10, missing=()):
    a, b = _pair(streams=streams, chunk_bytes=chunk_bytes)
    try:
        bids = []
        for i, p in enumerate(payloads):
            bid = ShuffleBlockId(0, i, 0)
            if i not in missing:
                b.register(bid, BytesBlock(p))
            bids.append(bid)
        bufs = [_buf(max(len(p), 1)) for p in payloads]
        reqs = a.fetch_blocks_by_block_ids(2, bids, bufs, [None] * len(bids))
        _drive(a, reqs)
        out = []
        for p, buf, r in zip(payloads, bufs, reqs):
            res = r.wait(0)
            if res.status == OperationStatus.SUCCESS:
                out.append(bytes(buf.host_view()[: res.stats.recv_size].tobytes()))
            else:
                out.append(None)
        return out
    finally:
        a.close()
        b.close()


class TestStripedOracle:
    PAYLOADS = [
        np.random.default_rng(3).integers(0, 256, size=n, dtype=np.uint8).tobytes()
        for n in (1 << 20, 3 * (1 << 18) + 17, 5, 1, 1 << 16)
    ]

    @pytest.mark.parametrize("streams", [2, 4])
    def test_striped_matches_single_frame(self, streams):
        oracle = _fetch_all(1, self.PAYLOADS)
        got = _fetch_all(streams, self.PAYLOADS)
        assert got == oracle

    def test_striped_with_missing_blocks(self):
        oracle = _fetch_all(1, self.PAYLOADS, missing={1, 3})
        got = _fetch_all(4, self.PAYLOADS, missing={1, 3})
        assert got == oracle
        assert got[1] is None and got[3] is None

    def test_chunk_smaller_than_block(self):
        # many chunks per block, odd remainder chunk
        p = [bytes(range(256)) * 600]  # 150 KiB
        assert _fetch_all(4, p, chunk_bytes=4096) == _fetch_all(1, p)

    def test_dead_server_fails_striped_batch(self):
        a, b = _pair(streams=4)
        try:
            bid = ShuffleBlockId(0, 0, 0)
            b.register(bid, BytesBlock(b"x" * 1024))
            buf = _buf(1024)
            reqs = a.fetch_blocks_by_block_ids(2, [bid], [buf], [None])
            _drive(a, reqs)  # establish group + one good fetch
            b.server.close()  # server gone: all lanes die
            buf2 = _buf(1024)
            reqs2 = a.fetch_blocks_by_block_ids(2, [bid], [buf2], [None])
            _drive(a, reqs2)
            assert reqs2[0].wait(0).status == OperationStatus.FAILURE
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# stripe reassembly under deliberately shuffled lane interleaving
# ---------------------------------------------------------------------------


class TestStripeReassembly:
    """Drive the transport's chunk/manifest callbacks directly — the exact
    code lane recv threads run — in adversarial orderings."""

    def _seed(self, a, tag, sizes):
        reqs = [Request(OperationStats()) for _ in sizes]
        bufs = [_buf(n) for n in sizes]
        with a._tag_lock:
            a._inflight[tag] = (reqs, bufs, [None] * len(sizes), None)
            a._stripe_rx[tag] = _StripeRx()
        return reqs, bufs

    def _manifest_hdr(self, tag, sizes):
        return (
            _TAG.pack(tag)
            + _COUNT.pack(len(sizes))
            + b"".join(_SIZE.pack(s) for s in sizes)
        )

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    @pytest.mark.parametrize("manifest_at", ["first", "middle", "last"])
    def test_shuffled_interleavings_complete_once(self, seed, manifest_at):
        a = PeerTransport(TpuShuffleConf(), executor_id=1)
        try:
            rng = random.Random(seed)
            payloads = [bytes([i]) * n for i, n in enumerate((5000, 0, 1, 12345))]
            sizes = [len(p) for p in payloads]
            tag = 1000 + seed
            reqs, bufs = self._seed(a, tag, [max(n, 1) for n in sizes])
            chunk = 512
            events = []
            for blk, p in enumerate(payloads):
                for off in range(0, len(p), chunk):
                    events.append(("chunk", blk, off, p[off : off + chunk]))
            rng.shuffle(events)
            idx = {"first": 0, "middle": len(events) // 2, "last": len(events)}[manifest_at]
            events.insert(idx, ("manifest",))
            completions = []
            for ev in events:
                if ev[0] == "manifest":
                    done = a._on_manifest(self._manifest_hdr(tag, sizes))
                else:
                    _, blk, off, data = ev
                    mv = a._chunk_buffers(tag, blk, off, len(data))
                    assert mv is not None
                    mv[:] = data
                    done = a._chunk_done(tag, len(data), True)
                if done is not None:
                    completions.append(done)
            assert len(completions) == 1  # completes exactly once
            assert a._stripe_rx == {}  # accounting fully drained
            assert a._scattering == {}
            a._handle_frame((AmId.FETCH_BLOCK_REQ_ACK, completions[0], b"", True))
            for p, buf, req in zip(payloads, bufs, reqs):
                res = req.wait(0)
                assert res.status == OperationStatus.SUCCESS
                assert buf.host_view()[: len(p)].tobytes() == p
        finally:
            a.close()

    def test_unknown_tag_chunk_is_drained_not_scattered(self):
        a = PeerTransport(TpuShuffleConf(), executor_id=1)
        try:
            assert a._chunk_buffers(999, 0, 0, 64) is None
            assert a._chunk_done(999, 64, False) is None  # no rx state: ignored
        finally:
            a.close()

    def test_oversized_chunk_rejected(self):
        a = PeerTransport(TpuShuffleConf(), executor_id=1)
        try:
            tag = 5
            self._seed(a, tag, [16])
            # offset+len beyond the result buffer: no view, drained instead
            assert a._chunk_buffers(tag, 0, 8, 16) is None
            assert a._chunk_buffers(tag, 1, 0, 8) is None  # bad block index
            with a._tag_lock:
                assert tag not in a._scattering
        finally:
            a.close()

    def test_scattering_counter_survives_concurrent_lanes(self):
        """Two lanes scattering one tag: the mark must persist until BOTH
        finish (a set would drop the sibling's mark on first done)."""
        a = PeerTransport(TpuShuffleConf(), executor_id=1)
        try:
            tag = 6
            self._seed(a, tag, [4096])
            mv1 = a._chunk_buffers(tag, 0, 0, 1024)
            mv2 = a._chunk_buffers(tag, 0, 1024, 1024)
            assert mv1 is not None and mv2 is not None
            with a._tag_lock:
                assert a._scattering[tag] == 2
            a._chunk_done(tag, 1024, True)
            with a._tag_lock:
                assert a._scattering[tag] == 1  # sibling still writing
            a._chunk_done(tag, 1024, True)
            with a._tag_lock:
                assert tag not in a._scattering
        finally:
            a.close()


# ---------------------------------------------------------------------------
# credit-budget accounting
# ---------------------------------------------------------------------------


class TestCreditGate:
    def test_never_exceeds_budget(self):
        gate = CreditGate(1000)
        peak = []
        stop = threading.Event()

        def worker():
            rng = random.Random(threading.get_ident())
            while not stop.is_set():
                n = rng.randint(1, 400)
                gate.acquire(n)
                peak.append(gate.used)
                time.sleep(0)
                gate.release(n)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.25)
        stop.set()
        for t in threads:
            t.join()
        assert max(peak) <= 1000
        assert gate.used == 0  # drains to zero

    def test_oversized_request_admitted_alone(self):
        gate = CreditGate(100)
        assert gate.acquire(500, timeout=1.0)  # nothing in flight: admitted
        assert not gate.try_acquire(1)  # and nothing else fits now
        gate.release(500)
        assert gate.used == 0

    def test_acquire_blocks_until_release(self):
        gate = CreditGate(100)
        gate.acquire(80)
        assert not gate.acquire(40, timeout=0.05)  # would exceed: times out
        done = threading.Event()

        def releaser():
            time.sleep(0.05)
            gate.release(80)
            done.set()

        threading.Thread(target=releaser).start()
        assert gate.acquire(40, timeout=2.0)
        done.wait(2.0)
        gate.release(40)
        assert gate.used == 0

    def test_stall_time_accounted(self):
        gate = CreditGate(10)
        gate.acquire(10)
        threading.Timer(0.05, gate.release, args=(10,)).start()
        gate.acquire(5, timeout=2.0)
        assert gate.stall_ns >= 25_000_000  # waited at least ~25ms

    def test_invalid_budget_rejected(self):
        with pytest.raises(ValueError):
            CreditGate(0)


class TestReaderCreditPipelining:
    def _reader(self, transport, pool, credit_bytes, sizes):
        return TpuShuffleReader(
            transport,
            executor_id=1,
            shuffle_id=0,
            start_partition=0,
            end_partition=1,
            num_mappers=len(sizes),
            block_sizes=lambda m, r: sizes[m],
            max_blocks_per_request=2,
            pool=pool,
            sender_of=lambda m: 2,
            credit_bytes=credit_bytes,
        )

    @pytest.mark.parametrize("credit_bytes", [0, 4096, 1 << 30])
    def test_pipelined_stream_matches_serial(self, credit_bytes):
        payloads = [bytes([40 + i]) * (100 + 512 * i) for i in range(9)]
        sizes = [len(p) for p in payloads]
        a, b = _pair(streams=1)
        pool = MemoryPool(TpuShuffleConf())
        try:
            for i, p in enumerate(payloads):
                b.register(ShuffleBlockId(0, i, 0), BytesBlock(p))
            reader = self._reader(a, pool, credit_bytes, sizes)
            got = []
            for blk in reader.fetch_blocks():
                got.append(bytes(blk.data))
                blk.release()
            assert got == payloads  # window order, every byte intact
            assert reader.metrics.remote_blocks_fetched == len(payloads)
            assert reader.metrics.remote_bytes_read == sum(sizes)
        finally:
            a.close()
            b.close()
            pool.close()

    def test_pipelined_over_striped_wire(self):
        payloads = [bytes([i]) * (1 << 16) for i in range(8)]
        sizes = [len(p) for p in payloads]
        a, b = _pair(streams=4, chunk_bytes=8192)
        pool = MemoryPool(TpuShuffleConf())
        try:
            for i, p in enumerate(payloads):
                b.register(ShuffleBlockId(0, i, 0), BytesBlock(p))
            reader = self._reader(a, pool, 1 << 17, sizes)
            got = [bytes(blk.data) for blk in reader.fetch_blocks()]
            assert got == payloads
        finally:
            a.close()
            b.close()
            pool.close()


# ---------------------------------------------------------------------------
# sanitizer-enabled pooled-rx release contract + batch checkout
# ---------------------------------------------------------------------------


class TestPooledRxRelease:
    def test_release_contract_under_sanitizer(self):
        """Fetched pooled blocks released by the consumer must recycle
        cleanly, and use-after-release must raise under sanitize mode."""
        payloads = [b"first-block-payload", b"second" * 100]
        a, b = _pair(streams=1)
        pool = MemoryPool(TpuShuffleConf(sanitize=True))
        try:
            for i, p in enumerate(payloads):
                b.register(ShuffleBlockId(0, i, 0), BytesBlock(p))
            reader = TpuShuffleReader(
                a, 1, 0, 0, 1, 2,
                block_sizes=lambda m, r: len(payloads[m]),
                pool=pool,
                sender_of=lambda m: 2,
                credit_bytes=1 << 20,
            )
            it = reader.fetch_blocks()
            blk = next(it)
            assert bytes(blk.data) == payloads[0]
            blk.release()
            with pytest.raises(SanitizerError, match="use-after-release"):
                _ = blk.data
            blk.release()  # idempotent in sanitize mode too
            rest = list(it)
            assert bytes(rest[-1].data) == payloads[-1]  # detached: still valid
        finally:
            a.close()
            b.close()
            pool.close()

    def test_get_many_order_sizes_and_recycle(self):
        pool = MemoryPool(TpuShuffleConf(sanitize=True))
        sizes = [100, 5000, 100, 64, 5000]
        blocks = pool.get_many(sizes)
        assert [b.size for b in blocks] == sizes
        assert len({id(b) for b in blocks}) == len(blocks)
        views = [b.host_view() for b in blocks]
        for i, v in enumerate(views):
            v[: sizes[i]] = i  # distinct backing storage
        for i, v in enumerate(views):
            assert (v[: sizes[i]] == i).all()
        del views
        for b in blocks:
            b.close()
        pool.close()  # no leaked slabs -> no ResourceWarning

    def test_get_many_rejects_bad_size(self):
        pool = MemoryPool(TpuShuffleConf())
        with pytest.raises(ValueError):
            pool.get_many([64, 0])
        pool.close()


# ---------------------------------------------------------------------------
# wire timeouts (spark.shuffle.tpu.wire.timeoutMs) — stalled peers die at the
# deadline instead of blocking a lane forever; idle connections are exempt
# ---------------------------------------------------------------------------


class TestWireTimeouts:
    def test_server_times_out_hung_midframe_client(self):
        """A client that stalls mid-frame-header is cut loose at the timeout
        (strict mid-frame read); an idle client that sent nothing is not."""
        srv = BlockServer(TpuShuffleConf(wire_timeout_ms=200))
        try:
            idle = socket.create_connection(srv.address, timeout=10)
            hung = socket.create_connection(srv.address, timeout=10)
            hung.sendall(b"\x01\x00\x00")  # 3 of 20 header bytes, then silence
            hung.settimeout(5)
            assert hung.recv(1) == b""  # server closed the hung conn
            hung.close()
            # the idle conn (zero bytes sent) must still be alive and serving
            time.sleep(0.3)  # well past wire_timeout_ms
            idle.sendall(
                pack_frame(AmId.FETCH_BLOCK_REQ, pack_batch_fetch_req(5, [ShuffleBlockId(0, 0, 0)]))
            )
            hdr = recv_exact(idle, FRAME_HEADER_SIZE)
            assert hdr is not None  # got a reply: conn survived idling
            idle.close()
        finally:
            srv.close()

    def test_client_times_out_midbody_with_addressed_error(self):
        """A server that stalls mid-ack-body fails the fetch at the client's
        timeout, and the error names the peer address and fetch tag."""
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        addr = lst.getsockname()

        def stalling_server():
            conn, _ = lst.accept()
            hdr = recv_exact(conn, FRAME_HEADER_SIZE)
            _, hlen, blen = unpack_frame_header(hdr)
            req_hdr = recv_exact(conn, hlen + blen)
            tag = _TAG.unpack_from(req_hdr)[0]
            # ack claims a 1000 B body but only 100 B ever arrive
            ack_hdr = _TAG.pack(tag) + _COUNT.pack(1) + _SIZE.pack(1000)
            conn.sendall(
                struct.pack("<IQQ", int(AmId.FETCH_BLOCK_REQ_ACK), len(ack_hdr), 1000)
                + ack_hdr
                + b"\x55" * 100
            )
            time.sleep(3)  # hold the socket open, never send the rest
            conn.close()

        t = threading.Thread(target=stalling_server, daemon=True)
        t.start()
        a = PeerTransport(TpuShuffleConf(wire_timeout_ms=200), executor_id=1)
        try:
            a.add_executor(9, f"{addr[0]}:{addr[1]}".encode())
            buf = _buf(1000)
            t0 = time.monotonic()
            [req] = a.fetch_blocks_by_block_ids(9, [ShuffleBlockId(0, 0, 0)], [buf], [None])
            _drive(a, [req], timeout=10)
            res = req.wait(1)
            assert res.status == OperationStatus.FAILURE
            assert "127.0.0.1" in str(res.error)  # peer named, not a bare reset
            assert time.monotonic() - t0 < 2.5  # timeout fired, no 3 s stall
        finally:
            a.close()
            lst.close()
            t.join(timeout=10)


# ---------------------------------------------------------------------------
# chaos on the striped wire (fault harness): reset mid-fetch, stalled lane
# ---------------------------------------------------------------------------


class TestChaosLanes:
    @pytest.fixture(autouse=True)
    def _clean_faults(self):
        from sparkucx_tpu.testing import faults

        faults.reset()
        yield
        faults.reset()

    def test_midfetch_reset_recovers_without_data_loss(self):
        """Severing the serving connection mid-fetch (connection reset) kills
        a lane of the stripe group; the reader's retry reforms the group (or
        falls back to a fresh connection) and every byte still arrives."""
        from sparkucx_tpu.testing import faults

        payloads = [bytes([i]) * (1 << 16) for i in range(6)]
        a, b = _pair(streams=4, chunk_bytes=8192)
        try:
            for i, p in enumerate(payloads):
                b.register(ShuffleBlockId(0, i, 0), BytesBlock(p))
            faults.arm(
                "peer.server.frame",
                faults.sever("reset mid-fetch"),
                times=1,
                match={"am_id": int(AmId.FETCH_BLOCK_REQ)},
            )
            reader = TpuShuffleReader(
                a, 1, 0, 0, 1, len(payloads),
                block_sizes=lambda m, r: len(payloads[m]),
                max_blocks_per_request=2,
                sender_of=lambda m: 2,
                fetch_retries=3,
                fetch_backoff_ms=5,
            )
            got = [bytes(blk.data) for blk in reader.fetch_blocks()]
            assert got == payloads  # no data loss through the reset
            assert faults.fired.get("peer.server.frame") == 1  # it DID fire
            assert reader.metrics.blocks_retried >= 1
        finally:
            a.close()
            b.close()

    def test_stalled_lane_times_out_then_retry_succeeds(self):
        """A lane that stalls forever (peer alive but wedged) trips the fetch
        deadline; the reader abandons the window and the retry refetches every
        byte.  Pins timeout-driven failover, not just reset-driven."""
        from sparkucx_tpu.testing import faults

        payloads = [b"stall-me" * 512, b"ok" * 300]
        a, b = _pair(streams=1, wire_timeout_ms=10_000)
        try:
            for i, p in enumerate(payloads):
                b.register(ShuffleBlockId(0, i, 0), BytesBlock(p))
            # wedge the server for the first fetch request only: the client
            # sees silence (not EOF), so only the deadline can save the window
            # the serve thread is wedged 1 s; retries starve on the same conn
            # until it wakes, so the retry budget (4 x 400 ms) must outlast it
            faults.arm(
                "peer.server.frame",
                faults.stall(1.0),
                times=1,
                match={"am_id": int(AmId.FETCH_BLOCK_REQ)},
            )
            reader = TpuShuffleReader(
                a, 1, 0, 0, 1, len(payloads),
                block_sizes=lambda m, r: len(payloads[m]),
                max_blocks_per_request=len(payloads),
                sender_of=lambda m: 2,
                fetch_retries=3,
                fetch_deadline_ms=400,
                fetch_backoff_ms=5,
            )
            t0 = time.monotonic()
            got = [bytes(blk.data) for blk in reader.fetch_blocks()]
            assert got == payloads
            assert reader.metrics.fetch_timeouts >= 1  # deadline actually fired
            assert time.monotonic() - t0 < 8  # bounded, not wedged
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# wire.checksum: CRC32C integrity on the striped wire (elasticity PR)
# ---------------------------------------------------------------------------


class TestCrc32c:
    def test_known_vectors(self):
        """google/crc32c reference vectors: byte-compatibility with every
        hardware implementation is the whole point of picking Castagnoli."""
        from sparkucx_tpu.utils.checksum import crc32c

        assert crc32c(b"") == 0x00000000
        assert crc32c(b"a") == 0xC1D04330
        assert crc32c(b"abc") == 0x364B3FB7
        assert crc32c(b"123456789") == 0xE3069283
        # the iSCSI 32x zero-byte vector (RFC 3720 B.4)
        assert crc32c(b"\x00" * 32) == 0x8A9136AA

    def test_incremental_matches_oneshot(self):
        from sparkucx_tpu.utils.checksum import crc32c

        data = bytes(range(256)) * 5
        assert crc32c(data[128:], crc32c(data[:128])) == crc32c(data)

    def test_detects_single_bit_flip(self):
        from sparkucx_tpu.utils.checksum import crc32c

        data = bytearray(b"x" * 100)
        want = crc32c(bytes(data))
        data[50] ^= 0x01
        assert crc32c(bytes(data)) != want


class TestWireChecksum:
    def test_checksum_off_frames_are_golden(self):
        """Knob off (the default): chunk headers carry NO crc trailer — the
        striped wire stays byte-identical to the pre-checksum protocol."""
        from sparkucx_tpu.core.definitions import CHUNK_HEADER_SIZE

        a, b = _pair(streams=2, chunk_bytes=512)
        try:
            assert not a.conf.wire_checksum
            bid = ShuffleBlockId(0, 0, 0)
            b.register(bid, BytesBlock(b"p" * 2000))
            seen = []
            orig = a._chunk_done

            def spy(tag, nbytes, scattered):
                seen.append(nbytes)
                return orig(tag, nbytes, scattered)

            a._chunk_done = spy
            buf = _buf(2048)
            reqs = a.fetch_blocks_by_block_ids(2, [bid], [buf], [None])
            _drive(a, reqs)
            assert reqs[0].wait(0).status == OperationStatus.SUCCESS
            assert seen, "no chunks arrived"
            # header-length detection is the protocol: knob off means every
            # header is exactly CHUNK_HEADER_SIZE (spy proves chunks flowed)
            assert CHUNK_HEADER_SIZE == 24
        finally:
            a.close()
            b.close()

    @pytest.mark.parametrize("streams", [2, 4])
    def test_checksum_on_clean_fetch(self, streams):
        payload = bytes(np.random.default_rng(5).integers(0, 256, 6000, dtype=np.uint8))
        a, b = _pair(streams=streams, chunk_bytes=1024, wire_checksum=True)
        try:
            bid = ShuffleBlockId(3, 0, 0)
            b.register(bid, BytesBlock(payload))
            buf = _buf(8192)
            reqs = a.fetch_blocks_by_block_ids(2, [bid], [buf], [None])
            _drive(a, reqs)
            res = reqs[0].wait(0)
            assert res.status == OperationStatus.SUCCESS, str(res.error)
            assert bytes(res.data.host_view()[: res.data.size]) == payload
        finally:
            a.close()
            b.close()

    def test_corrupted_chunk_raises_block_corrupt(self):
        """Payload garbled in flight (after the crc was computed) must surface
        as a typed BlockCorruptError, not silent garbage or a generic loss."""
        from sparkucx_tpu.core.operation import BlockCorruptError
        from sparkucx_tpu.testing import faults

        a, b = _pair(streams=2, chunk_bytes=1024, wire_checksum=True)
        try:
            bid = ShuffleBlockId(4, 0, 0)
            b.register(bid, BytesBlock(b"q" * 4000))
            faults.arm("peer.server.chunk", faults.garble(), times=1)
            buf = _buf(4096)
            reqs = a.fetch_blocks_by_block_ids(2, [bid], [buf], [None])
            _drive(a, reqs)
            res = reqs[0].wait(0)
            assert res.status == OperationStatus.FAILURE
            assert isinstance(res.error, BlockCorruptError), type(res.error)
            assert "crc32c" in str(res.error)
        finally:
            faults.reset()
            a.close()
            b.close()

    def test_corruption_failover_to_replica(self):
        """End to end: a corrupt primary fetch fails its lane, and the
        reader's retry failover refetches the block from the replica holder —
        'bytes arrived but are wrong' heals exactly like 'peer died'."""
        from sparkucx_tpu.testing import faults

        payloads = [b"heal-me" * 300]
        a, b = _pair(streams=2, chunk_bytes=1024, wire_checksum=True)
        try:
            b.register(ShuffleBlockId(0, 0, 0), BytesBlock(payloads[0]))
            faults.arm("peer.server.chunk", faults.garble(), times=1)
            reader = TpuShuffleReader(
                a, 1, 0, 0, 1, 1,
                block_sizes=lambda m, r: len(payloads[m]),
                sender_of=lambda m: 2,
                fetch_retries=2,
                fetch_backoff_ms=5,
            )
            got = [bytes(blk.data) for blk in reader.fetch_blocks()]
            assert got == payloads
            assert reader.metrics.blocks_retried >= 1
        finally:
            faults.reset()
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# bounded replicator (elasticity PR)
# ---------------------------------------------------------------------------


def _stage_rounds(t, sid, num_reducers=1, seed=0):
    rng = np.random.default_rng(seed)
    t.store.create_shuffle(sid, 1, num_reducers)
    w = t.store.map_writer(sid, 0)
    for r in range(num_reducers):
        w.write_partition(r, rng.integers(0, 256, 300, dtype=np.uint8).tobytes())
    w.commit()


class TestBoundedReplicator:
    def _pair_repl(self, **kw):
        kw.setdefault("staging_capacity_per_executor", 1 << 20)
        kw.setdefault("replication_factor", 1)
        conf = TpuShuffleConf(**kw)
        a = PeerTransport(conf, executor_id=0)
        b = PeerTransport(conf, executor_id=1)
        a.add_executor(1, b.init())
        a.init()
        b.add_executor(0, a.server.address_bytes())
        return a, b

    def test_single_worker_settles_many_seals(self):
        """Thread-per-seal is gone: many seals drain through ONE worker and
        all settle; the backlog gauge returns to zero."""
        from sparkucx_tpu.testing import faults

        a, b = self._pair_repl()
        try:
            for sid in range(5):
                _stage_rounds(a, sid, seed=sid)
                a.store.seal(sid)
            for sid in range(5):
                assert a.replication_wait(sid, timeout=10.0, strict=True)
            assert a.replica_stats["replica_backlog_bytes"] == 0
            assert a.replica_stats["pushed_rounds"] >= 5
        finally:
            a.close()
            b.close()

    def test_backlog_cap_drops_oldest(self):
        """Backlog over replication.maxBacklogBytes: the OLDEST queued shuffle
        is dropped (accounted in dropped_rounds), never an unbounded queue."""
        from sparkucx_tpu.testing import faults

        a, b = self._pair_repl(replication_max_backlog_bytes=1)
        try:
            faults.arm("replica.push", faults.stall(0.5))
            with a._tag_lock:  # simulate a stuck backlog from a slow successor
                a.replica_stats["replica_backlog_bytes"] = 10
            for sid in (21, 22, 23):
                _stage_rounds(a, sid, seed=sid)
                a.store.seal(sid)
            deadline = time.monotonic() + 3
            while a.replica_stats["dropped_rounds"] < 1 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert a.replica_stats["dropped_rounds"] >= 1
            faults.reset()
            with a._tag_lock:
                a.replica_stats["replica_backlog_bytes"] = 0
        finally:
            faults.reset()
            a.close()
            b.close()

    def test_strict_wait_names_stalled_successor(self):
        """An ack lost mid-apply leaves the push unsettled; strict wait raises
        a TransportError NAMING the successor whose acks never came."""
        from sparkucx_tpu.core.operation import TransportError
        from sparkucx_tpu.testing import faults

        a, b = self._pair_repl()
        try:
            faults.arm("replica.apply", faults.sever(), times=1)
            _stage_rounds(a, 5)
            a.store.seal(5)
            with pytest.raises(TransportError, match=r"successor executor\(s\) \[1\]"):
                a.replication_wait(5, timeout=0.7, strict=True)
        finally:
            faults.reset()
            a.close()
            b.close()

    def test_replica_put_checksum_discards_corrupt_round(self):
        """A REPLICA_PUT whose crc trailer does not match its body is
        discarded — no replica installed, no ack — and the serving thread
        survives to install the next (valid) round.  The trailer is detected
        by header length, so the receiver needs no conf agreement with the
        pusher (hand-crafted frames over a raw socket prove it)."""
        from sparkucx_tpu.core.definitions import pack_replica_put
        from sparkucx_tpu.utils.checksum import crc32c

        a, b = self._pair_repl()
        sock = None
        try:
            body = b"replica-round-payload" * 16
            sock = socket.create_connection(b.server.address, timeout=10)
            # round 0 targets (map 0, reduce 0) with a deliberately wrong crc
            bad = pack_replica_put(9, 0, 0, [(0, 0, len(body))]) + struct.pack(
                "<I", crc32c(body) ^ 0xDEADBEEF
            )
            sock.sendall(pack_frame(AmId.REPLICA_PUT, bad, body))
            # round 1 targets (map 0, reduce 1) with a valid crc
            good = pack_replica_put(9, 0, 1, [(0, 1, len(body))]) + struct.pack(
                "<I", crc32c(body)
            )
            sock.sendall(pack_frame(AmId.REPLICA_PUT, good, body))
            # the first (and only) ack on the wire is for the VALID round:
            # the corrupt one produced no ack, and the conn survived it
            hdr = recv_exact(sock, FRAME_HEADER_SIZE)
            am_id, hlen, blen = unpack_frame_header(hdr)
            recv_exact(sock, hlen + blen)
            assert am_id == AmId.REPLICA_ACK
            assert b.store.replica_view(9, 0, 0) is None
            assert b.store.replica_view(9, 0, 1) is not None
        finally:
            if sock is not None:
                sock.close()
            a.close()
            b.close()

    def test_checksum_on_replica_roundtrip(self):
        """Clean wire with checksum on: replicas install and ack normally."""
        a, b = self._pair_repl(wire_checksum=True)
        try:
            _stage_rounds(a, 12)
            a.store.seal(12)
            assert a.replication_wait(12, timeout=10.0, strict=True)
            assert b.store.replica_view(12, 0, 0) is not None
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# compress.codec: per-chunk page codecs on the striped wire (compression PR)
# ---------------------------------------------------------------------------


def _compressible_payloads():
    """Exchange-shaped payloads (u32 words: low-cardinality keys, runs,
    near-sequential columns) plus noise, empties, and sub-chunk blocks —
    every fallback path of the codec ext in one batch."""
    rng = np.random.default_rng(11)
    alpha = rng.integers(0, 50, size=1 << 15, dtype=np.uint64).astype("<u4")
    return [
        alpha.tobytes(),  # dictionary/rle-friendly
        bytes(1 << 16),  # zero runs
        (np.uint32(7) + np.cumsum(
            rng.integers(0, 9, size=1 << 14), dtype=np.int64
        ).astype(np.uint32)).astype("<u4").tobytes(),  # delta-friendly
        rng.integers(0, 256, size=(1 << 15) + 17, dtype=np.uint8).tobytes(),  # noise
        b"",  # empty block
        b"tiny",  # under the min-chunk gate
    ]


class TestWireCompression:
    def test_codec_wire_constants_pinned(self):
        """Codec ids and the chunk-header extension are wire format —
        renumbering or re-packing is a protocol break."""
        from sparkucx_tpu.core.definitions import (
            CHUNK_CODEC_EXT_SIZE,
            CHUNK_HEADER_SIZE,
            pack_chunk_codec_ext,
        )
        from sparkucx_tpu.utils.pagecodec import (
            CODEC_DELTA,
            CODEC_DICT,
            CODEC_RAW,
            CODEC_RLE,
        )

        assert (CODEC_RAW, CODEC_DICT, CODEC_RLE, CODEC_DELTA) == (0, 1, 2, 3)
        assert CHUNK_CODEC_EXT_SIZE == 8
        assert pack_chunk_codec_ext(2, 4096) == struct.pack("<II", 2, 4096)
        # header-length detection table: 24 plain, +8 codec, +4 crc (crc LAST)
        assert CHUNK_HEADER_SIZE == 24
        assert unpack_chunk_hdr(pack_chunk_hdr(9, 1, 2, 3) + pack_chunk_codec_ext(1, 8)) == (9, 1, 2, 3)

    def test_default_is_off(self):
        """codec=off is the default, keeping the golden frames above (single
        lane AND striped) byte-identical to the pre-compression protocol."""
        assert TpuShuffleConf().wire_compress_codec == "off"
        assert TpuShuffleConf().compress_min_chunk_bytes == 4096

    @pytest.mark.parametrize("codec", ["dict", "rle", "delta"])
    @pytest.mark.parametrize("streams", [1, 4])
    def test_compressed_fetch_matches_stock(self, codec, streams):
        """Oracle: a compressed fetch returns byte-for-byte what the stock
        (codec=off) wire returns, for every payload shape and lane count —
        including the raw-fallback and sub-chunk-gate paths."""
        payloads = _compressible_payloads()
        oracle = _fetch_all(1, payloads)

        a, b = _pair(
            streams=streams, chunk_bytes=16 << 10, wire_compress_codec=codec
        )
        try:
            bids = []
            for i, p in enumerate(payloads):
                bid = ShuffleBlockId(0, i, 0)
                b.register(bid, BytesBlock(p))
                bids.append(bid)
            bufs = [_buf(max(len(p), 1)) for p in payloads]
            reqs = a.fetch_blocks_by_block_ids(2, bids, bufs, [None] * len(bids))
            _drive(a, reqs)
            got = []
            for p, buf, r in zip(payloads, bufs, reqs):
                res = r.wait(0)
                assert res.status == OperationStatus.SUCCESS, str(res.error)
                got.append(bytes(buf.host_view()[: res.stats.recv_size].tobytes()))
            assert got == oracle
            snap = b.server.compress_snapshot()
            assert snap["encoded_chunks"] >= 1  # compression actually engaged
            assert snap["raw_chunks"] >= 1  # and the noise block fell back raw
            assert snap["wire_bytes"] < snap["raw_bytes"]
        finally:
            a.close()
            b.close()

    @pytest.mark.parametrize("checksum", [False, True])
    def test_garbled_compressed_chunk_raises_block_corrupt(self, checksum):
        """A compressed chunk garbled in flight surfaces as the SAME typed
        BlockCorruptError on both detection paths: the crc trailer when
        checksum is on (it covers the ENCODED bytes, so it fires before the
        decoder parses anything), the decoder's CodecError otherwise."""
        from sparkucx_tpu.core.operation import BlockCorruptError
        from sparkucx_tpu.testing import faults

        a, b = _pair(
            streams=2, chunk_bytes=1024,
            wire_compress_codec="rle", wire_checksum=checksum,
        )
        try:
            bid = ShuffleBlockId(4, 0, 0)
            b.register(bid, BytesBlock(bytes(64 << 10)))  # zeros: always encodes
            faults.arm("peer.server.chunk", faults.garble(), times=1)
            buf = _buf(64 << 10)
            reqs = a.fetch_blocks_by_block_ids(2, [bid], [buf], [None])
            _drive(a, reqs)
            res = reqs[0].wait(0)
            assert res.status == OperationStatus.FAILURE
            assert isinstance(res.error, BlockCorruptError), type(res.error)
            if checksum:
                assert "crc32c" in str(res.error)
        finally:
            faults.reset()
            a.close()
            b.close()

    def test_corruption_failover_heals_compressed_fetch(self):
        """End to end on the compressed wire: the decode failure kills the
        lane, and the reader's retry refetches the block intact — corruption
        enters the same failover path as a dead peer."""
        from sparkucx_tpu.testing import faults

        payloads = [bytes(16 << 10)]
        a, b = _pair(streams=2, chunk_bytes=1024, wire_compress_codec="rle")
        try:
            b.register(ShuffleBlockId(0, 0, 0), BytesBlock(payloads[0]))
            faults.arm("peer.server.chunk", faults.garble(), times=1)
            reader = TpuShuffleReader(
                a, 1, 0, 0, 1, 1,
                block_sizes=lambda m, r: len(payloads[m]),
                sender_of=lambda m: 2,
                fetch_retries=2,
                fetch_backoff_ms=5,
            )
            got = [bytes(blk.data) for blk in reader.fetch_blocks()]
            assert got == payloads
            assert reader.metrics.blocks_retried >= 1
        finally:
            faults.reset()
            a.close()
            b.close()

    def test_single_lane_with_codec_uses_chunk_frames(self):
        """compress.codec on forces the stripe (chunked) path even at
        streams=1 — the codec ext rides chunk headers, which the single-frame
        reply has nowhere to carry."""
        a, b = _pair(streams=1, wire_compress_codec="rle")
        try:
            bid = ShuffleBlockId(0, 0, 0)
            b.register(bid, BytesBlock(bytes(32 << 10)))
            buf = _buf(32 << 10)
            reqs = a.fetch_blocks_by_block_ids(2, [bid], [buf], [None])
            _drive(a, reqs)
            assert reqs[0].wait(0).status == OperationStatus.SUCCESS
            assert b.server._groups, "no stripe group formed for the codec path"
            assert b.server.compress_snapshot()["encoded_chunks"] >= 1
        finally:
            a.close()
            b.close()


class TestReplicaCompression:
    """REPLICA_PUT whole-round page compression: same codec ext, same
    discard-no-ack contract as a crc mismatch."""

    def _pair_repl(self, **kw):
        kw.setdefault("staging_capacity_per_executor", 1 << 20)
        kw.setdefault("replication_factor", 1)
        conf = TpuShuffleConf(**kw)
        a = PeerTransport(conf, executor_id=0)
        b = PeerTransport(conf, executor_id=1)
        a.add_executor(1, b.init())
        a.init()
        b.add_executor(0, a.server.address_bytes())
        return a, b

    def test_compressed_replica_roundtrip(self):
        """A compressible round pushed over a codec-on wire installs the
        exact original bytes on the successor (encode on push, decode on
        install)."""
        a, b = self._pair_repl(wire_compress_codec="rle")
        try:
            payload = bytes(4096)  # zero page: always encodes
            a.store.create_shuffle(31, 1, 1)
            w = a.store.map_writer(31, 0)
            w.write_partition(0, payload)
            w.commit()
            a.store.seal(31)
            assert a.replication_wait(31, timeout=10.0, strict=True)
            view = b.store.replica_view(31, 0, 0)
            assert view is not None
            arr, off, ln = view
            assert ln == len(payload)
            got = arr.reshape(-1).view(np.uint8)[off : off + ln].tobytes()
            assert got == payload
        finally:
            a.close()
            b.close()

    def test_corrupt_codec_round_discarded_no_ack(self):
        """A REPLICA_PUT whose codec ext claims an encoded body that fails to
        decode is discarded without an ack — and the serving thread survives
        to install the next (valid, raw-codec-ext) round.  Hand-crafted
        frames: the receiver needs no conf agreement with the pusher."""
        from sparkucx_tpu.core.definitions import pack_chunk_codec_ext, pack_replica_put
        from sparkucx_tpu.utils.pagecodec import CODEC_RAW, CODEC_RLE

        a, b = self._pair_repl()
        sock = None
        try:
            body = b"replica-round-payload" * 16
            sock = socket.create_connection(b.server.address, timeout=10)
            # round 0: codec ext claims an rle page, body is garbage for it
            bad = pack_replica_put(8, 0, 0, [(0, 0, 64)]) + pack_chunk_codec_ext(
                CODEC_RLE, 64
            )
            sock.sendall(pack_frame(AmId.REPLICA_PUT, bad, body))
            # round 1: raw codec ext with the true length — valid
            good = pack_replica_put(8, 0, 1, [(0, 1, len(body))]) + pack_chunk_codec_ext(
                CODEC_RAW, len(body)
            )
            sock.sendall(pack_frame(AmId.REPLICA_PUT, good, body))
            hdr = recv_exact(sock, FRAME_HEADER_SIZE)
            am_id, hlen, blen = unpack_frame_header(hdr)
            recv_exact(sock, hlen + blen)
            assert am_id == AmId.REPLICA_ACK  # first ack is for the VALID round
            assert b.store.replica_view(8, 0, 0) is None
            assert b.store.replica_view(8, 0, 1) is not None
        finally:
            if sock is not None:
                sock.close()
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# tiered eviction x the wire: rounds demoted mid-fetch serve from every tier
# ---------------------------------------------------------------------------


class TestDemoteMidFetch:
    @pytest.mark.parametrize("streams", [1, 2])
    def test_round_demoted_between_windows_bit_identical(self, streams):
        """A sealed round demoted host->disk BETWEEN fetch windows keeps
        serving bit-identically: the next fetch lands on the memmap tier and
        the eviction manager transparently restages the round to RAM
        (service/eviction.py restage-on-fetch), on both the monolithic and
        the striped serve paths."""
        from sparkucx_tpu.service.eviction import EvictionManager

        a, b = _pair(streams=streams)
        try:
            rng = np.random.default_rng(11)
            b.store.create_shuffle(3, 1, 4)
            w = b.store.map_writer(3, 0)
            oracle = {}
            for r in range(4):
                data = rng.integers(0, 256, size=700 + 41 * r, dtype=np.uint8).tobytes()
                oracle[r] = data
                w.write_partition(r, data)
            w.commit()
            b.store.seal(3)
            ev = EvictionManager(b.store)
            b.store.eviction = ev

            def fetch(r):
                buf = _buf(len(oracle[r]))
                req = a.fetch_block(2, 3, 0, r, buf)
                _drive(a, [req])
                res = req.wait(0)
                assert res.status == OperationStatus.SUCCESS, str(res.error)
                return buf.host_view()[: buf.size].tobytes()

            assert fetch(0) == oracle[0]  # served from the resident tier
            while b.store.round_tier(3, 0) != "disk":  # demote mid-stream
                assert b.store.demote_round(3, 0) is not None
            assert fetch(1) == oracle[1]  # cold fetch: restage-on-fetch
            assert b.store.round_tier(3, 0) == "host"
            assert ev.eviction_stats()["restages"] >= 1
            assert fetch(2) == oracle[2]
            assert fetch(3) == oracle[3]
        finally:
            a.close()
            b.close()
