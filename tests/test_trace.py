"""Span tracer (utils/trace.py) — chrome-trace export and hot-path wiring.
An aux subsystem with no reference counterpart (SURVEY.md section 5.1)."""

import json
import threading

import numpy as np
import pytest

from sparkucx_tpu.utils import trace as trace_mod
from sparkucx_tpu.utils.trace import Tracer


class TestTracer:
    def test_disabled_records_nothing(self):
        t = Tracer(enabled=False)
        with t.span("x"):
            pass
        t.instant("y")
        assert t.events == []

    def test_span_event_shape(self):
        t = Tracer(enabled=True)
        with t.span("exchange.superstep", shuffle_id=3):
            pass
        [ev] = t.events
        assert ev["name"] == "exchange.superstep" and ev["ph"] == "X"
        assert ev["dur"] >= 0 and ev["args"] == {"shuffle_id": 3}

    def test_nested_and_exception_spans(self):
        t = Tracer(enabled=True)
        with pytest.raises(ValueError):
            with t.span("outer"):
                with t.span("inner"):
                    raise ValueError("boom")
        names = [e["name"] for e in t.events]
        assert names == ["inner", "outer"]  # closed innermost-first, both recorded

    def test_export_valid_chrome_trace(self, tmp_path):
        t = Tracer(enabled=True)
        with t.span("a"):
            t.instant("marker", category="debug", extra=object())
        path = tmp_path / "trace.json"
        n = t.export(str(path))
        doc = json.loads(path.read_text())
        assert n == 2 and len(doc["traceEvents"]) == 2
        marker = next(e for e in doc["traceEvents"] if e["ph"] == "i")
        assert isinstance(marker["args"]["extra"], str)  # non-JSON values stringified

    def test_thread_ids_distinguish_tracks(self):
        t = Tracer(enabled=True)

        def work():
            with t.span("w"):
                pass

        th = threading.Thread(target=work)
        th.start()
        th.join()
        with t.span("main"):
            pass
        tids = {e["tid"] for e in t.events}
        assert len(tids) == 2


class TestHotPathWiring:
    def test_exchange_emits_spans(self):
        from sparkucx_tpu.config import TpuShuffleConf
        from sparkucx_tpu.transport.tpu import TpuShuffleCluster

        trace_mod.TRACER.clear()
        trace_mod.TRACER.enable()
        try:
            conf = TpuShuffleConf(
                staging_capacity_per_executor=1 << 20, block_alignment=128, num_executors=2
            )
            cluster = TpuShuffleCluster(conf, num_executors=2)
            cluster.create_shuffle(0, 2, 2)
            for m in range(2):
                t = cluster.transport(cluster.meta(0).map_owner[m])
                w = t.store.map_writer(0, m)
                for r in range(2):
                    w.write_partition(r, np.full(300, m * 2 + r, np.uint8).tobytes())
                t.commit_block(w.commit().pack())
            cluster.run_exchange(0)
            names = [e["name"] for e in trace_mod.TRACER.events]
            assert "exchange.superstep" in names
            assert "exchange.seal" in names
            assert "exchange.collective" in names
            assert "exchange.d2h" in names
            # nesting: superstep duration covers the collective
            sup = next(e for e in trace_mod.TRACER.events if e["name"] == "exchange.superstep")
            col = next(e for e in trace_mod.TRACER.events if e["name"] == "exchange.collective")
            assert sup["ts"] <= col["ts"] and sup["ts"] + sup["dur"] >= col["ts"] + col["dur"]
        finally:
            trace_mod.TRACER.disable()
            trace_mod.TRACER.clear()
