"""Tests for the shuffle exchange collective on the virtual 8-device CPU mesh.

The dense lowering executes here; the ragged lowering (TPU-only kernel) is checked
down to StableHLO.  Both produce the same tight sender-major receive layout, so
these oracle tests pin the contract for both.
"""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops.exchange import (
    ExchangeSpec,
    build_exchange,
    exclusive_cumsum,
    make_mesh,
    oracle_exchange,
    pack_chunks_slots,
    unpack_received,
)

N = 8
LANE = 32           # 128-byte rows in tests (lane=128 / 512 B on real TPU)
ROW_BYTES = LANE * 4
SLOT_ROWS = 64      # per-peer region: 8 KiB


def _spec(impl="dense"):
    return ExchangeSpec(
        num_executors=N,
        send_rows=N * SLOT_ROWS,
        recv_rows=N * SLOT_ROWS,
        lane=LANE,
        impl=impl,
    )


def _run_exchange(chunks, spec, mesh, fn):
    bufs, sizes = zip(*[pack_chunks_slots(chunks[i], SLOT_ROWS, ROW_BYTES) for i in range(N)])
    data = np.concatenate(bufs, axis=0)
    size_mat = np.stack(sizes).astype(np.int32)
    data_j = jax.device_put(data, NamedSharding(mesh, P("ex", None)))
    sm_j = jax.device_put(size_mat, NamedSharding(mesh, P("ex", None)))
    recv, recv_sizes = fn(data_j, sm_j)
    return np.asarray(recv), np.asarray(recv_sizes)


def _padded(chunk):
    pad = (-len(chunk)) % ROW_BYTES
    return chunk + b"\x00" * pad


def _verify_against_oracle(chunks, recv, recv_sizes, spec):
    padded = [[_padded(c) for c in row] for row in chunks]
    expected = oracle_exchange(padded)
    for j in range(N):
        shard = recv[j * spec.recv_rows : (j + 1) * spec.recv_rows].reshape(-1).view(np.uint8).tobytes()
        total = int(recv_sizes[j].sum()) * ROW_BYTES
        assert shard[:total] == expected[j], f"receiver {j} mismatch"
        per_sender = unpack_received(shard, recv_sizes[j], ROW_BYTES)
        for i in range(N):
            assert per_sender[i][: len(chunks[i][j])] == chunks[i][j]


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


@pytest.fixture(scope="module")
def dense_fn(mesh):
    return build_exchange(mesh, _spec())


class TestDenseExchange:
    def test_random_skewed_vs_oracle(self, mesh, dense_fn, rng):
        spec = dense_fn.spec
        max_bytes = SLOT_ROWS * ROW_BYTES // 2
        chunks = [
            [rng.integers(0, 256, size=int(rng.integers(0, max_bytes)), dtype=np.uint8).tobytes() for _ in range(N)]
            for _ in range(N)
        ]
        recv, recv_sizes = _run_exchange(chunks, spec, mesh, dense_fn)
        _verify_against_oracle(chunks, recv, recv_sizes, spec)

    def test_empty_chunks(self, mesh, dense_fn):
        # Empty partitions are the common case in skewed shuffles.
        chunks = [[b"" for _ in range(N)] for _ in range(N)]
        chunks[3][5] = b"only-block" * 3
        recv, recv_sizes = _run_exchange(chunks, dense_fn.spec, mesh, dense_fn)
        assert recv_sizes[5][3] == 1  # 30 bytes -> 1 row
        assert recv_sizes.sum() == 1
        _verify_against_oracle(chunks, recv, recv_sizes, dense_fn.spec)

    def test_identity_diagonal(self, mesh, dense_fn):
        # Every executor keeps one local chunk (self-send over the collective).
        chunks = [[b"" if i != j else bytes([i]) * 200 for j in range(N)] for i in range(N)]
        recv, recv_sizes = _run_exchange(chunks, dense_fn.spec, mesh, dense_fn)
        _verify_against_oracle(chunks, recv, recv_sizes, dense_fn.spec)

    def test_reuse_compiled_across_supersteps(self, mesh, dense_fn):
        # One compiled exchange serves many supersteps (no retrace): different data.
        for step in range(3):
            chunks = [
                [bytes([step, i, j]) * (10 * (i + j + 1)) for j in range(N)] for i in range(N)
            ]
            recv, recv_sizes = _run_exchange(chunks, dense_fn.spec, mesh, dense_fn)
            _verify_against_oracle(chunks, recv, recv_sizes, dense_fn.spec)

    def test_full_slots(self, mesh, dense_fn, rng):
        spec = dense_fn.spec
        full = SLOT_ROWS * ROW_BYTES
        chunks = [
            [rng.integers(0, 256, size=full, dtype=np.uint8).tobytes() for _ in range(N)]
            for _ in range(N)
        ]
        recv, recv_sizes = _run_exchange(chunks, spec, mesh, dense_fn)
        assert int(recv_sizes.sum()) == N * N * SLOT_ROWS
        _verify_against_oracle(chunks, recv, recv_sizes, spec)


class TestRaggedLowering:
    def test_ragged_lowers_to_stablehlo(self, mesh):
        # XLA:CPU can't execute ragged-all-to-all, but tracing/lowering must work —
        # this pins the TPU path's graph without TPU hardware.
        from sparkucx_tpu.ops._compat import HAS_RAGGED_ALL_TO_ALL

        if not HAS_RAGGED_ALL_TO_ALL:
            pytest.skip("jax.lax.ragged_all_to_all absent on this JAX (< 0.5)")
        spec = _spec(impl="ragged")
        fn = build_exchange(mesh, spec)
        data = jax.ShapeDtypeStruct((N * spec.send_rows, LANE), np.int32)
        sizes = jax.ShapeDtypeStruct((N, N), np.int32)
        text = fn.lower(data, sizes).as_text()
        assert "ragged_all_to_all" in text or "ragged-all-to-all" in text

    def test_auto_resolves_dense_on_cpu(self, mesh):
        fn = build_exchange(mesh, _spec(impl="auto"))
        assert fn.spec.impl == "dense"


class TestLocalLowering:
    """The n=1 degenerate exchange lowers to the Pallas DMA prefix copy on
    TPU ('local'); its resolve/validate logic is platform-independent and the
    kernel itself is exercised by bench.py's integrity gate on hardware."""

    def test_auto_resolves_local_on_tpu_n1(self):
        spec = ExchangeSpec(num_executors=1, send_rows=64, recv_rows=64)
        assert spec.resolve_impl(platform="tpu").impl == "local"

    def test_auto_resolves_ragged_on_tpu_n_gt_1(self):
        spec = ExchangeSpec(num_executors=4, send_rows=64, recv_rows=64)
        assert spec.resolve_impl(platform="tpu").impl == "ragged"

    def test_auto_resolves_dense_on_cpu_n1(self):
        spec = ExchangeSpec(num_executors=1, send_rows=64, recv_rows=64)
        assert spec.resolve_impl(platform="cpu").impl == "dense"

    def test_local_rejected_for_multi_executor(self):
        spec = ExchangeSpec(num_executors=2, send_rows=64, recv_rows=64, impl="local")
        with pytest.raises(ValueError, match="n=1 degenerate"):
            spec.validate()


class TestPacking:
    def test_slot_packing_offsets(self):
        buf, sizes = pack_chunks_slots([b"a" * 100, b"b" * 300], slot_rows=8, row_bytes=128)
        assert sizes.tolist() == [1, 3]  # 100 B -> 1 row, 300 B -> 3 rows
        raw = buf.reshape(-1).view(np.uint8)
        assert raw[:100].tobytes() == b"a" * 100
        assert raw[8 * 128 : 8 * 128 + 300].tobytes() == b"b" * 300

    def test_slot_overflow_raises(self):
        with pytest.raises(ValueError, match="exceeds slot"):
            pack_chunks_slots([b"x" * 2048], slot_rows=8, row_bytes=128)

    def test_unpack_received(self):
        shard = b"A" * 256 + b"B" * 128
        parts = unpack_received(shard, np.array([2, 1]), 128)
        assert parts == [b"A" * 256, b"B" * 128]


class TestSpec:
    def test_exclusive_cumsum(self):
        import jax.numpy as jnp

        got = exclusive_cumsum(jnp.array([3, 1, 4, 1]))
        assert got.tolist() == [0, 3, 4, 8]

    def test_mesh_size_mismatch_raises(self, mesh):
        with pytest.raises(ValueError, match="mesh size"):
            build_exchange(mesh, ExchangeSpec(num_executors=4, send_rows=64, recv_rows=64))

    def test_slot_divisibility(self, mesh):
        with pytest.raises(ValueError, match="divisible"):
            build_exchange(
                mesh, ExchangeSpec(num_executors=N, send_rows=1001, recv_rows=1001, impl="dense")
            )

    def test_row_bytes(self):
        assert _spec().row_bytes == ROW_BYTES
        assert ExchangeSpec(num_executors=1, send_rows=8, recv_rows=8).row_bytes == 512
