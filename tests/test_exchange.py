"""Tests for the shuffle exchange collective on the virtual 8-device CPU mesh.

The dense lowering executes here; the ragged lowering (TPU-only kernel) is checked
down to StableHLO.  Both produce the same tight sender-major receive layout, so
these oracle tests pin the contract for both.
"""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops.exchange import (
    ExchangeSpec,
    build_exchange,
    exclusive_cumsum,
    make_mesh,
    oracle_exchange,
    pack_chunks_peer_major,
    staging_layout,
    unpack_received,
)

N = 8
ALIGN = 128
EB = 4  # int32 lanes


def _spec(send_cap=1024, recv_cap=4096, impl="dense"):
    return ExchangeSpec(
        num_executors=N, send_capacity=send_cap, recv_capacity=recv_cap,
        dtype=np.dtype(np.int32), impl=impl,
    )


def _run_exchange(chunks, spec, mesh, fn):
    slot = spec.slot_capacity if spec.impl == "dense" else None
    bufs, sizes = zip(
        *[
            pack_chunks_peer_major(chunks[i], spec.send_capacity * EB, ALIGN, EB, slot_elems=slot)
            for i in range(N)
        ]
    )
    data = np.concatenate([b.view(np.int32) for b in bufs])
    size_mat = np.stack(sizes).astype(np.int32)
    data_j = jax.device_put(data, NamedSharding(mesh, P("ex")))
    sm_j = jax.device_put(size_mat, NamedSharding(mesh, P("ex", None)))
    recv, recv_sizes = fn(data_j, sm_j)
    return np.asarray(recv), np.asarray(recv_sizes)


def _padded(chunk):
    pad = (-len(chunk)) % ALIGN
    return chunk + b"\x00" * pad


def _verify_against_oracle(chunks, recv, recv_sizes, spec):
    padded = [[_padded(c) for c in row] for row in chunks]
    expected = oracle_exchange(padded)
    for j in range(N):
        shard = recv[j * spec.recv_capacity : (j + 1) * spec.recv_capacity].tobytes()
        total = int(recv_sizes[j].sum()) * EB
        assert shard[:total] == expected[j], f"receiver {j} mismatch"
        per_sender = unpack_received(shard, recv_sizes[j], EB)
        for i in range(N):
            assert per_sender[i][: len(chunks[i][j])] == chunks[i][j]


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


@pytest.fixture(scope="module")
def dense_fn(mesh):
    return build_exchange(mesh, _spec())


class TestDenseExchange:
    def test_random_skewed_vs_oracle(self, mesh, dense_fn, rng):
        spec = dense_fn.spec
        max_bytes = spec.slot_capacity * EB // 2
        chunks = [
            [rng.integers(0, 256, size=int(rng.integers(0, max_bytes)), dtype=np.uint8).tobytes() for _ in range(N)]
            for _ in range(N)
        ]
        recv, recv_sizes = _run_exchange(chunks, spec, mesh, dense_fn)
        _verify_against_oracle(chunks, recv, recv_sizes, spec)

    def test_empty_chunks(self, mesh, dense_fn):
        # Empty partitions are the common case in skewed shuffles.
        chunks = [[b"" for _ in range(N)] for _ in range(N)]
        chunks[3][5] = b"only-block" * 3
        recv, recv_sizes = _run_exchange(chunks, dense_fn.spec, mesh, dense_fn)
        assert recv_sizes[5][3] == ALIGN // EB
        assert recv_sizes.sum() == ALIGN // EB
        _verify_against_oracle(chunks, recv, recv_sizes, dense_fn.spec)

    def test_identity_diagonal(self, mesh, dense_fn, rng):
        # Every executor keeps one local chunk (self-send over the collective).
        chunks = [
            [b"" if i != j else bytes([i]) * 200 for j in range(N)] for i in range(N)
        ]
        recv, recv_sizes = _run_exchange(chunks, dense_fn.spec, mesh, dense_fn)
        _verify_against_oracle(chunks, recv, recv_sizes, dense_fn.spec)

    def test_reuse_compiled_across_supersteps(self, mesh, dense_fn, rng):
        # One compiled exchange serves many supersteps (no retrace): different data.
        for step in range(3):
            chunks = [
                [bytes([step, i, j]) * (10 * (i + j + 1)) for j in range(N)] for i in range(N)
            ]
            recv, recv_sizes = _run_exchange(chunks, dense_fn.spec, mesh, dense_fn)
            _verify_against_oracle(chunks, recv, recv_sizes, dense_fn.spec)

    def test_full_slots(self, mesh, dense_fn, rng):
        spec = dense_fn.spec
        full = spec.slot_capacity * EB
        chunks = [
            [rng.integers(0, 256, size=full, dtype=np.uint8).tobytes() for _ in range(N)]
            for _ in range(N)
        ]
        recv, recv_sizes = _run_exchange(chunks, spec, mesh, dense_fn)
        assert int(recv_sizes.sum()) == N * N * spec.slot_capacity
        _verify_against_oracle(chunks, recv, recv_sizes, spec)


class TestRaggedLowering:
    def test_ragged_lowers_to_stablehlo(self, mesh):
        # XLA:CPU can't execute ragged-all-to-all, but tracing/lowering must work —
        # this pins the TPU path's graph without TPU hardware.
        spec = _spec(impl="ragged")
        fn = build_exchange(mesh, spec)
        data = jax.ShapeDtypeStruct((N * spec.send_capacity,), np.int32)
        sizes = jax.ShapeDtypeStruct((N, N), np.int32)
        text = fn.lower(data, sizes).as_text()
        assert "ragged_all_to_all" in text or "ragged-all-to-all" in text

    def test_auto_resolves_dense_on_cpu(self, mesh):
        fn = build_exchange(mesh, _spec(impl="auto"))
        assert fn.spec.impl == "dense"


class TestPacking:
    def test_tight_packing_offsets(self):
        buf, sizes = pack_chunks_peer_major([b"a" * 100, b"b" * 300], 4096, ALIGN, EB)
        assert sizes.tolist() == [ALIGN // EB, 3 * ALIGN // EB]  # 300 B pads to 384
        assert buf[:100].tobytes() == b"a" * 100
        assert buf[ALIGN : ALIGN + 300].tobytes() == b"b" * 300

    def test_slot_packing_offsets(self):
        buf, sizes = pack_chunks_peer_major([b"a" * 100, b"b" * 300], 4096, ALIGN, EB, slot_elems=256)
        assert buf[:100].tobytes() == b"a" * 100
        assert buf[1024 : 1024 + 300].tobytes() == b"b" * 300

    def test_overflow_raises(self):
        with pytest.raises(ValueError, match="overflow"):
            pack_chunks_peer_major([b"x" * 4096, b"y" * 4096], 4096, ALIGN, EB)

    def test_slot_overflow_raises(self):
        with pytest.raises(ValueError, match="exceeds slot"):
            pack_chunks_peer_major([b"x" * 2048], 4096, ALIGN, EB, slot_elems=256)

    def test_alignment_must_match_dtype(self):
        with pytest.raises(ValueError, match="multiple"):
            pack_chunks_peer_major([b"x"], 4096, 3, EB)


class TestSpec:
    def test_exclusive_cumsum(self):
        import jax.numpy as jnp

        got = exclusive_cumsum(jnp.array([3, 1, 4, 1]))
        assert got.tolist() == [0, 3, 4, 8]

    def test_mesh_size_mismatch_raises(self, mesh):
        with pytest.raises(ValueError, match="mesh size"):
            build_exchange(mesh, ExchangeSpec(num_executors=4, send_capacity=64, recv_capacity=64))

    def test_dense_divisibility(self, mesh):
        with pytest.raises(ValueError, match="divisible"):
            build_exchange(mesh, _spec(send_cap=1001, impl="dense"))

    def test_staging_layout(self):
        ragged_tight = ExchangeSpec(
            num_executors=N, send_capacity=1024, recv_capacity=4096, impl="ragged", layout="tight"
        )
        assert staging_layout(ragged_tight) is None
        assert staging_layout(_spec(impl="dense")) == 1024 // N

    def test_dense_requires_slot_layout(self, mesh):
        with pytest.raises(ValueError, match="slot layout"):
            build_exchange(
                mesh,
                ExchangeSpec(
                    num_executors=N, send_capacity=1024, recv_capacity=1024,
                    impl="dense", layout="tight",
                ),
            )
