"""End-to-end shuffle through TpuShuffleCluster on the virtual 8-executor mesh.

This is the minimum end-to-end slice of SURVEY.md section 7: M mappers write
partition blocks into per-executor staging, ONE collective superstep moves
everything, R reducers fetch and verify against a CPU shuffle oracle — the
GroupByTest-equivalent without Spark.
"""

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus, TransportError
from sparkucx_tpu.transport.tpu import TpuShuffleCluster

N_EXEC = 8


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


@pytest.fixture(scope="module")
def cluster():
    conf = TpuShuffleConf(
        staging_capacity_per_executor=1 << 20, block_alignment=128, num_executors=N_EXEC
    )
    return TpuShuffleCluster(conf, num_executors=N_EXEC)


def _run_shuffle(cluster, shuffle_id, num_mappers, num_reducers, rng, max_block=2000):
    """Write random blocks, commit, exchange. Returns the oracle dict."""
    meta = cluster.create_shuffle(shuffle_id, num_mappers, num_reducers)
    oracle = {}
    for m in range(num_mappers):
        owner = meta.map_owner[m]
        t = cluster.transport(owner)
        w = t.store.map_writer(shuffle_id, m)
        for r in range(num_reducers):
            size = int(rng.integers(0, max_block))
            payload = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    cluster.run_exchange(shuffle_id)
    return meta, oracle


class TestEndToEndShuffle:
    def test_full_shuffle_vs_oracle(self, cluster, rng):
        M, R = 16, 24
        meta, oracle = _run_shuffle(cluster, 0, M, R, rng)
        # every reducer fetches every one of its blocks on its owning executor
        for r in range(R):
            consumer = meta.owner_of_reduce(r)
            t = cluster.transport(consumer)
            bids = [ShuffleBlockId(0, m, r) for m in range(M)]
            bufs = [_buf(4096) for _ in range(M)]
            reqs = t.fetch_blocks_by_block_ids(consumer, bids, bufs, [None] * M)
            while not all(q.completed() for q in reqs):
                t.progress()
            for m in range(M):
                res = reqs[m].wait(1)
                assert res.status == OperationStatus.SUCCESS, str(res.error)
                assert bufs[m].host_view()[: bufs[m].size].tobytes() == oracle[(m, r)]

    def test_skewed_and_empty_partitions(self, cluster, rng):
        M, R = 4, 8
        meta = cluster.create_shuffle(1, M, R)
        # all data goes to reducer 5; everything else empty
        big = rng.integers(0, 256, size=30_000, dtype=np.uint8).tobytes()
        for m in range(M):
            t = cluster.transport(meta.map_owner[m])
            w = t.store.map_writer(1, m)
            for r in range(R):
                w.write_partition(r, big if r == 5 else b"")
            t.commit_block(w.commit().pack())
        cluster.run_exchange(1)
        consumer = meta.owner_of_reduce(5)
        t = cluster.transport(consumer)
        bufs = [_buf(32768) for _ in range(M)]
        reqs = t.fetch_blocks_by_block_ids(
            consumer, [ShuffleBlockId(1, m, 5) for m in range(M)], bufs, [None] * M
        )
        for m in range(M):
            assert reqs[m].wait(1).status == OperationStatus.SUCCESS
            assert bufs[m].host_view()[: bufs[m].size].tobytes() == big
        # empty block fetch succeeds with zero size
        consumer0 = meta.owner_of_reduce(0)
        t0 = cluster.transport(consumer0)
        [req] = t0.fetch_blocks_by_block_ids(consumer0, [ShuffleBlockId(1, 0, 0)], [_buf(64)], [None])
        res = req.wait(1)
        assert res.status == OperationStatus.SUCCESS
        assert res.stats.recv_size == 0

    def test_fetch_wrong_owner_fails(self, cluster, rng):
        meta, _ = _run_shuffle(cluster, 2, 4, 8, rng, max_block=100)
        r = 0
        wrong = (meta.owner_of_reduce(r) + 1) % N_EXEC
        t = cluster.transport(wrong)
        [req] = t.fetch_blocks_by_block_ids(wrong, [ShuffleBlockId(2, 0, r)], [_buf(256)], [None])
        res = req.wait(1)
        assert res.status == OperationStatus.FAILURE
        assert "owned by" in str(res.error)

    def test_exchange_requires_all_commits(self, cluster, rng):
        meta = cluster.create_shuffle(3, 4, 4)
        t = cluster.transport(meta.map_owner[0])
        w = t.store.map_writer(3, 0)
        w.write_partition(0, b"x")
        t.commit_block(w.commit().pack())
        with pytest.raises(TransportError, match="before all maps committed"):
            cluster.run_exchange(3)

    def test_double_exchange_rejected(self, cluster, rng):
        _run_shuffle(cluster, 4, 2, 2, rng, max_block=50)
        with pytest.raises(TransportError, match="already exchanged"):
            cluster.run_exchange(4)

    def test_fetch_before_exchange_fails(self, cluster, rng):
        meta = cluster.create_shuffle(5, 1, 1)
        t = cluster.transport(meta.owner_of_reduce(0))
        [req] = t.fetch_blocks_by_block_ids(0, [ShuffleBlockId(5, 0, 0)], [_buf(8)], [None])
        assert req.wait(1).status == OperationStatus.FAILURE


class TestMultiRound:
    def test_spill_shuffle_end_to_end(self, rng):
        # Staging deliberately too small for one round: data spills across
        # multiple collective rounds and every block still arrives intact.
        conf = TpuShuffleConf(
            staging_capacity_per_executor=N_EXEC * 4096,  # 4 KiB per peer region
            block_alignment=128,
            num_executors=N_EXEC,
        )
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        M, R = 3 * N_EXEC, 8  # 3 maps/executor x 2 KiB padded blocks > 4 KiB regions
        meta = cluster.create_shuffle(0, M, R)
        oracle = {}
        for m in range(M):
            t = cluster.transport(meta.map_owner[m])
            w = t.store.map_writer(0, m)
            for r in range(R):
                payload = rng.integers(0, 256, size=2000, dtype=np.uint8).tobytes()
                oracle[(m, r)] = payload
                w.write_partition(r, payload)
            t.commit_block(w.commit().pack())
        rounds = max(t.store.num_rounds(0) for t in cluster.transports)
        assert rounds > 1, "test should actually spill"
        cluster.run_exchange(0)
        for r in range(R):
            consumer = meta.owner_of_reduce(r)
            t = cluster.transport(consumer)
            bufs = [_buf(4096) for _ in range(M)]
            reqs = t.fetch_blocks_by_block_ids(
                consumer, [ShuffleBlockId(0, m, r) for m in range(M)], bufs, [None] * M
            )
            for m in range(M):
                res = reqs[m].wait(5)
                assert res.status == OperationStatus.SUCCESS, str(res.error)
                assert bufs[m].host_view()[: bufs[m].size].tobytes() == oracle[(m, r)]


class TestPullFallback:
    def test_fetch_block_from_peer_store(self, cluster, rng):
        # The straggler path: read a peer's staged block directly, pre-exchange.
        meta = cluster.create_shuffle(6, 2, 2)
        owner = meta.map_owner[1]
        t_owner = cluster.transport(owner)
        w = t_owner.store.map_writer(6, 1)
        w.write_partition(0, b"straggler-block")
        w.write_partition(1, b"")
        t_owner.commit_block(w.commit().pack())

        fetcher = cluster.transport((owner + 1) % N_EXEC)
        out = _buf(64)
        req = fetcher.fetch_block(owner, 6, 1, 0, out)
        while not req.completed():
            fetcher.progress()
        assert req.wait(1).status == OperationStatus.SUCCESS
        assert out.host_view()[: out.size].tobytes() == b"straggler-block"

    def test_fetch_block_missing(self, cluster):
        cluster.create_shuffle(7, 1, 1)
        fetcher = cluster.transport(0)
        req = fetcher.fetch_block(0, 7, 0, 0, _buf(8))
        while not req.completed():
            fetcher.progress()
        assert req.wait(1).status == OperationStatus.FAILURE


class TestStats:
    def test_fetch_stats_recv_size(self, cluster, rng):
        meta, oracle = _run_shuffle(cluster, 8, 2, 2, rng, max_block=500)
        r = 0
        consumer = meta.owner_of_reduce(r)
        t = cluster.transport(consumer)
        [req] = t.fetch_blocks_by_block_ids(consumer, [ShuffleBlockId(8, 1, r)], [_buf(1024)], [None])
        res = req.wait(1)
        assert res.stats.recv_size == len(oracle[(1, r)])
        assert res.stats.elapsed_ns() > 0


class TestRegistry:
    def test_upstream_registry_parity(self, cluster):
        from sparkucx_tpu.core.block import BytesBlock

        t = cluster.transport(0)
        bid = ShuffleBlockId(99, 0, 0)
        t.register(bid, BytesBlock(b"reg"))
        assert t.registered_block(bid) is not None
        t.unregister_shuffle(99)
        assert t.registered_block(bid) is None


class TestHierarchicalCluster:
    """numSlices > 1 routes the cluster's superstep through the two-phase
    ICI+DCN exchange (ops/hierarchy.py) — same results, different lowering."""

    def test_full_shuffle_vs_oracle_two_slices(self, rng):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 20,
            block_alignment=128,
            num_executors=N_EXEC,
            num_slices=2,
        )
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        M, R = 8, 16
        meta, oracle = _run_shuffle(cluster, 0, M, R, rng)
        for r in range(R):
            consumer = meta.owner_of_reduce(r)
            t = cluster.transport(consumer)
            bids = [ShuffleBlockId(0, m, r) for m in range(M)]
            bufs = [_buf(4096) for _ in range(M)]
            t.fetch_blocks_by_block_ids(consumer, bids, bufs, [None] * M)
            for m, buf in enumerate(bufs):
                got = buf.host_view()[: buf.size].tobytes()
                assert got == oracle[(m, r)], f"mismatch map={m} reduce={r}"

    def test_invalid_factorization_rejected(self):
        with pytest.raises(ValueError, match="divisible"):
            TpuShuffleConf().replace(num_executors=8, num_slices=3)
