"""Multi-tenant shuffle service tests (ROADMAP 4).

Pins the subsystem's four contracts:

* **Registry + admission control** — per-app registration with HBM byte
  quotas, charge/release accounting at region-allocation time, typed
  ``TenantQuotaExceededError`` / ``UnknownTenantError``, per-tenant
  shuffle-id namespaces (``sid_for`` / ``translate``), per-tenant CreditGates.
* **Tiered eviction** — epoch/LRU demotion of sealed rounds
  (HBM -> host -> disk) through ``HbmBlockStore.demote_round``, transparent
  restage-on-fetch, footprint-ordered restage planning (arXiv:2112.01075),
  ``eviction_stats`` telemetry — all bit-identical at every tier.
* **Serving plane** — the shared-selector Reactor multiplexes many idle
  connections over a bounded worker pool; the tenant ``app_id`` rides the
  FETCH_BLOCK_REQ extension (absent by default: golden single-tenant frames
  unchanged) and tenant errors come back as addressed size codes the client
  maps to the typed exceptions — fail-fast, never retried.
* **Quota x eviction interplay** — demotion to disk returns the tenant's
  HBM bytes, restage re-charges FIRST, so an over-quota tenant's cold fetch
  fails typed while the round stays serveable on disk.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import (
    OperationStatus,
    TenantQuotaExceededError,
    TransportError,
    UnknownTenantError,
)
from sparkucx_tpu.service.eviction import EvictionManager
from sparkucx_tpu.service.reactor import Reactor
from sparkucx_tpu.service.tenants import TENANT_SID_BASE, TenantRegistry
from sparkucx_tpu.shuffle.reader import TpuShuffleReader
from sparkucx_tpu.store.hbm_store import HbmBlockStore
from sparkucx_tpu.transport.peer import (
    PeerTransport,
    pack_batch_fetch_req,
    unpack_batch_fetch_req,
    unpack_fetch_req_app_id,
)
from sparkucx_tpu.transport.pipeline import CreditGate

ALIGN = 128


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


def _wait(t, req, timeout=10.0):
    deadline = time.monotonic() + timeout
    while not req.completed() and time.monotonic() < deadline:
        t.progress()
        time.sleep(0.001)
    return req.wait(1)


# ---------------------------------------------------------------------------
# tenant registry + admission control
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_register_and_resolve(self):
        reg = TenantRegistry(default_quota_bytes=1000)
        t = reg.register("app-a")
        assert t.hbm_quota_bytes == 1000  # default applied
        assert reg.register("app-b", hbm_quota_bytes=5).hbm_quota_bytes == 5
        assert reg.resolve("app-a") is t
        assert reg.known("app-a") and not reg.known("ghost")
        assert reg.app_ids() == ["app-a", "app-b"]

    def test_unknown_tenant_typed(self):
        reg = TenantRegistry()
        with pytest.raises(UnknownTenantError) as ei:
            reg.resolve("ghost")
        assert ei.value.app_id == "ghost"
        assert isinstance(ei.value, TransportError)  # old catch-sites work

    def test_charge_release_usage(self):
        reg = TenantRegistry()
        reg.register("a", hbm_quota_bytes=100)
        reg.charge("a", 0, 60)
        assert reg.usage("a") == 60
        reg.charge("a", 0, 40)  # exactly at quota admits
        with pytest.raises(TenantQuotaExceededError) as ei:
            reg.charge("a", 7, 1)
        e = ei.value
        assert (e.app_id, e.shuffle_id) == ("a", 7)
        assert (e.requested, e.used, e.quota) == (1, 100, 100)
        reg.release("a", 30)
        assert reg.usage("a") == 70
        reg.charge("a", 0, 30)  # headroom restored

    def test_zero_quota_is_unlimited(self):
        reg = TenantRegistry()
        reg.register("a")  # default quota 0
        reg.charge("a", 0, 1 << 40)
        assert reg.usage("a") == 1 << 40

    def test_release_tolerates_unknown_and_floor(self):
        reg = TenantRegistry()
        reg.release("ghost", 10)  # cleanup path must never raise
        reg.register("a", hbm_quota_bytes=10)
        reg.release("a", 99)
        assert reg.usage("a") == 0  # floored, never negative

    def test_sid_namespace_isolated_per_tenant(self):
        reg = TenantRegistry()
        reg.register("a")
        reg.register("b")
        sa = reg.sid_for("a", 0)
        sb = reg.sid_for("b", 0)
        assert sa >= TENANT_SID_BASE and sb >= TENANT_SID_BASE
        assert sa != sb  # same local id, disjoint internal ids
        assert reg.sid_for("a", 0) == sa  # get-or-allocate is stable
        assert reg.translate("a", 0) == sa
        assert reg.translate("b", 0) == sb

    def test_translate_unknown_local_sid_passes_through(self):
        # known tenant + never-allocated local id: untranslated, so the store
        # reports its usual unknown-shuffle error (retryable block-not-found
        # on the wire), unlike the typed fail-fast tenant errors
        reg = TenantRegistry()
        reg.register("a")
        assert reg.translate("a", 42) == 42

    def test_translate_unknown_tenant_raises(self):
        reg = TenantRegistry()
        with pytest.raises(UnknownTenantError):
            reg.translate("ghost", 0)
        with pytest.raises(UnknownTenantError):
            reg.sid_for("ghost", 0)

    def test_reregister_keeps_usage_updates_budget(self):
        reg = TenantRegistry()
        reg.register("a", hbm_quota_bytes=100)
        reg.charge("a", 0, 80)
        t = reg.register("a", hbm_quota_bytes=200)  # executor restart
        assert t.used_bytes == 80 and t.hbm_quota_bytes == 200

    def test_unregister_drops_charges_and_sids(self):
        reg = TenantRegistry()
        reg.register("a")
        sid = reg.sid_for("a", 0)
        reg.charge("a", 0, 50)
        reg.unregister("a")
        reg.unregister("a")  # idempotent
        assert not reg.known("a")
        reg.register("a")
        assert reg.usage("a") == 0
        assert reg.sid_for("a", 0) != sid  # namespace was reclaimed

    def test_gate_per_tenant(self):
        reg = TenantRegistry(default_credit_bytes=1 << 20)
        reg.register("a")
        reg.register("b", credit_bytes=0)
        ga = reg.gate("a")
        assert isinstance(ga, CreditGate)
        assert reg.gate("a") is ga  # lazily created once
        assert reg.gate("b") is None  # no budget -> no gating
        with pytest.raises(UnknownTenantError):
            reg.gate("ghost")

    def test_stats_snapshot(self):
        reg = TenantRegistry()
        reg.register("a", hbm_quota_bytes=100)
        reg.sid_for("a", 0)
        reg.sid_for("a", 1)
        reg.charge("a", 0, 10)
        assert reg.stats() == {
            "a": {"used_bytes": 10, "quota_bytes": 100, "num_shuffles": 2}
        }


class TestStoreAdmission:
    def _store(self, capacity=1 << 20):
        return HbmBlockStore(
            TpuShuffleConf(
                staging_capacity_per_executor=capacity, block_alignment=ALIGN
            )
        )

    def test_write_charges_quota(self):
        s = self._store()
        reg = TenantRegistry()
        s.tenants = reg
        reg.register("a", hbm_quota_bytes=1 << 20)
        sid = reg.sid_for("a", 0)
        s.create_shuffle(sid, 1, 1, app_id="a")
        w = s.map_writer(sid, 0)
        w.write_partition(0, b"x" * 300)
        w.commit()
        assert reg.usage("a") >= 300  # padded region bytes claimed
        s.close()

    def test_over_quota_write_raises_typed_and_isolates_neighbor(self):
        s = self._store()
        reg = TenantRegistry()
        s.tenants = reg
        reg.register("small", hbm_quota_bytes=256)
        reg.register("big", hbm_quota_bytes=1 << 20)
        sid_small = reg.sid_for("small", 0)
        sid_big = reg.sid_for("big", 0)
        s.create_shuffle(sid_small, 1, 1, app_id="small")
        s.create_shuffle(sid_big, 1, 1, app_id="big")
        with pytest.raises(TenantQuotaExceededError) as ei:
            w = s.map_writer(sid_small, 0)
            w.write_partition(0, b"x" * 4096)
        assert ei.value.app_id == "small"
        # the neighbor tenant is unaffected by small's rejection
        w = s.map_writer(sid_big, 0)
        w.write_partition(0, b"y" * 4096)
        w.commit()
        assert s.read_block(sid_big, 0, 0) == b"y" * 4096
        assert reg.usage("big") >= 4096
        s.close()

    def test_create_shuffle_unknown_tenant_raises(self):
        s = self._store()
        s.tenants = TenantRegistry()
        with pytest.raises(UnknownTenantError):
            s.create_shuffle(TENANT_SID_BASE, 1, 1, app_id="ghost")
        s.close()

    def test_remove_shuffle_releases_charge(self):
        s = self._store()
        reg = TenantRegistry()
        s.tenants = reg
        reg.register("a", hbm_quota_bytes=1 << 20)
        sid = reg.sid_for("a", 0)
        s.create_shuffle(sid, 1, 1, app_id="a")
        w = s.map_writer(sid, 0)
        w.write_partition(0, b"x" * 1000)
        w.commit()
        assert reg.usage("a") > 0
        s.remove_shuffle(sid)
        assert reg.usage("a") == 0
        s.close()

    def test_untenanted_shuffle_never_charged(self):
        # tenants registry attached but app_id omitted: the single-tenant
        # path, byte-identical behavior, no admission checks
        s = self._store()
        reg = TenantRegistry()
        s.tenants = reg
        reg.register("a", hbm_quota_bytes=1)
        s.create_shuffle(0, 1, 1)
        w = s.map_writer(0, 0)
        w.write_partition(0, b"x" * 4096)
        w.commit()
        assert reg.usage("a") == 0
        s.close()


# ---------------------------------------------------------------------------
# tiered eviction: demote / restage / plan / stats
# ---------------------------------------------------------------------------


def _cpu_device():
    import jax

    return jax.devices("cpu")[0]


def _demote_to_disk(s, sid, round_idx=0):
    """Demote one round all the way down (1 tier from host, 2 from hbm)."""
    while s.round_tier(sid, round_idx) != "disk":
        assert s.demote_round(sid, round_idx) is not None
    return s.round_tier(sid, round_idx)


def _sealed_store(
    payload=b"", num_blocks=2, capacity=1 << 20, app=None, reg=None, device=None
):
    """One sealed single-round shuffle; returns (store, sid, oracle).
    With ``device`` the seal stages to a jax.Array (the 'hbm' tier even on
    the CPU backend); without, payloads stay host-resident ('host')."""
    s = HbmBlockStore(
        TpuShuffleConf(staging_capacity_per_executor=capacity, block_alignment=ALIGN),
        device=device,
    )
    if reg is not None:
        s.tenants = reg
    sid = reg.sid_for(app, 0) if app is not None else 0
    s.create_shuffle(sid, 1, num_blocks, app_id=app)
    w = s.map_writer(sid, 0)
    oracle = {}
    rng = np.random.default_rng(3)
    for r in range(num_blocks):
        data = payload or rng.integers(0, 256, size=500 + 37 * r, dtype=np.uint8).tobytes()
        oracle[r] = data
        w.write_partition(r, data)
    w.commit()
    s.seal(sid)
    return s, sid, oracle


class TestTieredEviction:
    def test_demote_descends_tiers_and_serves_each(self):
        s, sid, oracle = _sealed_store(device=_cpu_device())
        try:
            assert s.round_tier(sid, 0) == "hbm"
            assert s.demote_round(sid, 0) == "hbm->host"
            assert s.round_tier(sid, 0) == "host"
            for r, want in oracle.items():
                assert s.read_block(sid, 0, r) == want
            assert s.demote_round(sid, 0) == "host->disk"
            assert s.round_tier(sid, 0) == "disk"
            for r, want in oracle.items():
                assert s.read_block(sid, 0, r) == want  # memmap tier serves
            assert s.demote_round(sid, 0) is None  # floor reached
        finally:
            s.close()

    def test_restage_round_trip_bit_identical(self):
        s, sid, oracle = _sealed_store()
        try:
            _demote_to_disk(s, sid)
            assert s.restage_round(sid, 0)
            assert s.round_tier(sid, 0) == "host"
            for r, want in oracle.items():
                assert s.read_block(sid, 0, r) == want
            assert not s.restage_round(sid, 0)  # already resident
        finally:
            s.close()

    def test_unsealed_rounds_are_not_candidates(self):
        s = HbmBlockStore(
            TpuShuffleConf(staging_capacity_per_executor=1 << 20, block_alignment=ALIGN)
        )
        try:
            s.create_shuffle(0, 1, 1)
            w = s.map_writer(0, 0)
            w.write_partition(0, b"live")
            w.commit()
            assert s.eviction_candidates() == []
            assert s.demote_round(0, 0) is None
        finally:
            s.close()

    def test_manager_epoch_demotes_lru_first(self):
        s, sid_cold, oracle_cold = _sealed_store(device=_cpu_device())
        try:
            s.create_shuffle(1, 1, 1)
            w = s.map_writer(1, 0)
            w.write_partition(0, b"hot" * 100)
            w.commit()
            s.seal(1)
            ev = EvictionManager(s)
            s.eviction = ev
            assert s.read_block(1, 0, 0) == b"hot" * 100  # bump hot's LRU clock
            assert ev.run_epoch(max_demotions=1) == 1
            assert s.round_tier(sid_cold, 0) == "host"  # never-fetched went first
            assert s.round_tier(1, 0) == "hbm"
            # a full sweep demotes everything one more tier each epoch
            assert ev.run_epoch() == 2
            assert s.round_tier(sid_cold, 0) == "disk"
            assert s.round_tier(1, 0) == "host"
            assert ev.eviction_stats()["demotions"] == 3
            for r, want in oracle_cold.items():
                assert s.read_block(sid_cold, 0, r) == want
        finally:
            s.close()

    def test_restage_on_fetch_from_disk(self):
        s, sid, oracle = _sealed_store()
        try:
            ev = EvictionManager(s)
            s.eviction = ev
            _demote_to_disk(s, sid)
            assert s.read_block(sid, 0, 0) == oracle[0]  # fetch restages...
            assert s.round_tier(sid, 0) == "host"  # ...the whole round to RAM
            stats = ev.eviction_stats()
            assert stats["restages"] == 1
            assert stats["restage_p99_ns"] > 0
        finally:
            s.close()

    def test_restage_plan_orders_by_footprint(self):
        s = HbmBlockStore(
            TpuShuffleConf(staging_capacity_per_executor=1 << 20, block_alignment=ALIGN)
        )
        try:
            for sid, size in ((0, 4096), (1, 256), (2, 1024)):
                s.create_shuffle(sid, 1, 1)
                w = s.map_writer(sid, 0)
                w.write_partition(0, b"x" * size)
                w.commit()
                s.seal(sid)
            ev = EvictionManager(s)
            s.eviction = ev
            for _ in range(2):
                ev.run_epoch()  # everything to disk
            plan = ev.restage_plan([(0, 0), (1, 0), (2, 0)])
            # ascending staged footprint: peak transient staging grows slowest
            assert plan == [(1, 0), (2, 0), (0, 0)]
            assert ev.restage_all(0) == 1
            assert s.round_tier(0, 0) == "host"
        finally:
            s.close()

    def test_background_epochs_demote_without_manual_sweeps(self):
        s, sid, oracle = _sealed_store()
        ev = EvictionManager(s, epoch_ms=20)
        s.eviction = ev
        try:
            ev.start()
            deadline = time.monotonic() + 10
            while s.round_tier(sid, 0) != "disk" and time.monotonic() < deadline:
                time.sleep(0.02)
            assert s.round_tier(sid, 0) == "disk"
            assert s.read_block(sid, 0, 0) == oracle[0]
        finally:
            ev.close()
            s.close()


class TestQuotaEvictionInterplay:
    def test_demote_to_disk_releases_quota_restage_recharges(self):
        reg = TenantRegistry()
        reg.register("a", hbm_quota_bytes=1 << 20)
        s, sid, oracle = _sealed_store(app="a", reg=reg, device=_cpu_device())
        try:
            charged = reg.usage("a")
            assert charged > 0
            assert s.demote_round(sid, 0) == "hbm->host"  # still RAM: charged
            assert reg.usage("a") == charged
            assert s.demote_round(sid, 0) == "host->disk"  # bytes returned
            assert reg.usage("a") == 0
            assert s.restage_round(sid, 0)
            assert reg.usage("a") == charged
        finally:
            s.close()

    def test_over_quota_restage_fails_typed_round_stays_on_disk(self):
        reg = TenantRegistry()
        reg.register("a", hbm_quota_bytes=1 << 20)
        s, sid, oracle = _sealed_store(app="a", reg=reg)
        ev = EvictionManager(s)
        s.eviction = ev
        try:
            _demote_to_disk(s, sid)
            reg.register("a", hbm_quota_bytes=16)  # shrink below the round
            with pytest.raises(TenantQuotaExceededError):
                s.read_block(sid, 0, 0)  # restage-on-fetch hits admission
            assert s.round_tier(sid, 0) == "disk"  # round survived, on disk
            reg.register("a", hbm_quota_bytes=1 << 20)  # headroom restored
            assert s.read_block(sid, 0, 0) == oracle[0]
            assert s.round_tier(sid, 0) == "host"
        finally:
            s.close()


# ---------------------------------------------------------------------------
# the reactor serving plane
# ---------------------------------------------------------------------------


class TestReactor:
    def _echo_reactor(self, workers=2):
        r = Reactor(workers, name="test-reactor")
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(128)
        addr = srv.getsockname()

        def serve_once(conn):
            data = conn.recv(64)
            if not data:
                return False
            conn.sendall(data.upper())
            return True

        def on_accept(conn):
            conn.setblocking(True)
            r.add_connection(conn, serve_once)

        r.add_listener(srv, on_accept)
        return r, addr

    def test_many_connections_one_loop(self):
        r, addr = self._echo_reactor(workers=4)
        try:
            socks = [socket.create_connection(addr, timeout=5) for _ in range(32)]
            for i, c in enumerate(socks):  # every held connection serves...
                c.sendall(b"m%03d" % i)
            for i, c in enumerate(socks):
                assert c.recv(64) == b"M%03d" % i
            for i, c in enumerate(socks):  # ...and re-arms for the next frame
                c.sendall(b"x%03d" % i)
                assert c.recv(64) == b"X%03d" % i
            deadline = time.monotonic() + 5
            while r.num_connections < 32 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert r.num_connections == 32
            for c in socks:
                c.close()
            deadline = time.monotonic() + 5
            while r.num_connections > 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert r.num_connections == 0  # EOF drops, on the loop's clock
        finally:
            r.close()

    def test_on_close_runs_once_on_drop(self):
        r = Reactor(1, name="test-reactor-drop")
        dropped = []
        a, b = socket.socketpair()
        try:
            r.add_connection(b, lambda c: False, on_close=dropped.append)
            a.sendall(b"wake")
            deadline = time.monotonic() + 5
            while not dropped and time.monotonic() < deadline:
                time.sleep(0.01)
            assert dropped == [b]
        finally:
            a.close()
            r.close()

    def test_close_is_idempotent_and_rejects_new_work(self):
        r, addr = self._echo_reactor()
        r.close()
        r.close()
        with pytest.raises(RuntimeError, match="closed"):
            r.add_connection(socket.socket(), lambda c: False)


# ---------------------------------------------------------------------------
# wire: the self-describing tenant extension + typed addressed errors
# ---------------------------------------------------------------------------


class TestWireExtension:
    def test_default_frames_byte_identical(self):
        # golden pin: no app_id -> EXACTLY the pre-tenant request bytes
        bids = [ShuffleBlockId(1, 2, 3), ShuffleBlockId(4, 5, 6)]
        import struct

        want = struct.pack("<Q", 9) + struct.pack("<I", 2)
        for b in bids:
            want += struct.pack("<iii", b.shuffle_id, b.map_id, b.reduce_id)
        golden = pack_batch_fetch_req(9, bids)
        assert golden == want
        assert unpack_fetch_req_app_id(golden, 2) is None

    def test_extension_roundtrip_invisible_to_triple_parser(self):
        bids = [ShuffleBlockId(0, 1, 2)]
        hdr = pack_batch_fetch_req(5, bids, app_id="app-x")
        assert unpack_fetch_req_app_id(hdr, 1) == "app-x"
        tag, parsed = unpack_batch_fetch_req(hdr)
        assert tag == 5 and parsed == bids  # ext residue ignored
        assert hdr[: len(pack_batch_fetch_req(5, bids))] == pack_batch_fetch_req(5, bids)

    def test_malformed_extension_reads_as_absent(self):
        bids = [ShuffleBlockId(0, 1, 2)]
        base = pack_batch_fetch_req(5, bids)
        import struct

        assert unpack_fetch_req_app_id(base + b"\x01", 1) is None  # truncated len
        assert unpack_fetch_req_app_id(
            base + struct.pack("<I", 99) + b"ab", 1
        ) is None  # length overruns
        assert unpack_fetch_req_app_id(
            base + struct.pack("<I", 0), 1
        ) is None  # empty app_id


def _tenant_server(apps, payload_of, num_blocks=2, workers=2):
    """Tenants-enabled server with one sealed shuffle per app; returns
    (server transport, registry, addr, {app: {reduce: payload}})."""
    conf = TpuShuffleConf(
        tenants_enabled=True,
        server_workers=workers,
        staging_capacity_per_executor=1 << 20,
        wire_timeout_ms=5000,
    )
    reg = TenantRegistry()
    srv = PeerTransport(conf, executor_id=1)
    srv.store.tenants = reg
    addr = srv.init()
    oracle = {}
    for app in apps:
        reg.register(app, hbm_quota_bytes=1 << 20)
        sid = reg.sid_for(app, 0)
        srv.store.create_shuffle(sid, 1, num_blocks, app_id=app)
        w = srv.store.map_writer(sid, 0)
        oracle[app] = {}
        for r in range(num_blocks):
            data = payload_of(app, r)
            oracle[app][r] = data
            w.write_partition(r, data)
        w.commit()
        srv.store.seal(sid)
    return srv, reg, addr, oracle


def _tenant_client(addr, app_id, executor_id=7):
    conf = TpuShuffleConf(
        tenants_enabled=True,
        staging_capacity_per_executor=1 << 20,
        wire_timeout_ms=5000,
    )
    c = PeerTransport(conf, executor_id=executor_id)
    c.app_id = app_id
    c.init()
    c.add_executor(1, addr)
    return c


class TestWireMultiTenant:
    def test_eight_apps_fetch_their_own_namespaces(self):
        apps = [f"app-{i}" for i in range(8)]
        payload_of = lambda app, r: (app.encode() + b":%d:" % r) * 40
        srv, reg, addr, oracle = _tenant_server(apps, payload_of)
        clients = []
        try:
            clients = [
                _tenant_client(addr, app, executor_id=10 + i)
                for i, app in enumerate(apps)
            ]
            reqs = []
            for c in clients:
                for r in (0, 1):
                    buf = _buf(len(oracle[c.app_id][r]))
                    # tenant-LOCAL shuffle id 0: every app names the same id,
                    # the server's registry translation keeps them disjoint
                    req = c.fetch_block(1, 0, 0, r, buf)
                    reqs.append((c, r, buf, req))
            for c, r, buf, req in reqs:
                res = _wait(c, req)
                assert res.status == OperationStatus.SUCCESS, str(res.error)
                assert buf.host_view()[: buf.size].tobytes() == oracle[c.app_id][r]
        finally:
            for c in clients:
                c.close()
            srv.close()

    def test_unknown_tenant_fails_typed_over_wire(self):
        srv, reg, addr, oracle = _tenant_server(["app-a"], lambda a, r: b"x" * 100)
        ghost = None
        try:
            ghost = _tenant_client(addr, "ghost")
            buf = _buf(100)
            res = _wait(ghost, ghost.fetch_block(1, 0, 0, 0, buf))
            assert res.status == OperationStatus.FAILURE
            assert isinstance(res.error, UnknownTenantError)
            assert res.error.app_id == "ghost"
            assert "rejected the fetch" in str(res.error)
        finally:
            if ghost is not None:
                ghost.close()
            srv.close()

    def test_untenanted_client_on_tenant_server_compat(self):
        # app_id=None -> no wire extension -> untranslated sid: the golden
        # single-tenant path keeps working against a tenants-enabled server
        srv, reg, addr, _ = _tenant_server(["app-a"], lambda a, r: b"x" * 100)
        plain = None
        try:
            srv.store.create_shuffle(5, 1, 1)  # untenanted global sid
            w = srv.store.map_writer(5, 0)
            w.write_partition(0, b"single-tenant" * 10)
            w.commit()
            plain = _tenant_client(addr, None)
            buf = _buf(130)
            res = _wait(plain, plain.fetch_block(1, 5, 0, 0, buf))
            assert res.status == OperationStatus.SUCCESS, str(res.error)
            assert buf.host_view()[: buf.size].tobytes() == b"single-tenant" * 10
            # and a tenant-namespaced sid is invisible without the extension
            buf2 = _buf(100)
            res2 = _wait(plain, plain.fetch_block(1, 0, 0, 0, buf2))
            assert res2.status == OperationStatus.FAILURE
            assert not isinstance(
                res2.error, (UnknownTenantError, TenantQuotaExceededError)
            )  # plain block-not-found, the retryable kind
        finally:
            if plain is not None:
                plain.close()
            srv.close()

    def test_quota_exceeded_restage_fails_typed_then_recovers(self):
        srv, reg, addr, oracle = _tenant_server(
            ["app-a"], lambda a, r: b"Q" * 600, num_blocks=2
        )
        client = None
        try:
            ev = EvictionManager(srv.store)
            srv.store.eviction = ev
            sid = reg.translate("app-a", 0)
            _demote_to_disk(srv.store, sid)
            assert reg.usage("app-a") == 0
            reg.register("app-a", hbm_quota_bytes=16)  # no restage headroom
            client = _tenant_client(addr, "app-a")
            buf = _buf(600)
            res = _wait(client, client.fetch_block(1, 0, 0, 0, buf))
            assert res.status == OperationStatus.FAILURE
            assert isinstance(res.error, TenantQuotaExceededError)
            assert res.error.app_id == "app-a"
            # headroom restored: restage-on-fetch serves bit-identical bytes
            reg.register("app-a", hbm_quota_bytes=1 << 20)
            buf2 = _buf(600)
            res2 = _wait(client, client.fetch_block(1, 0, 0, 0, buf2))
            assert res2.status == OperationStatus.SUCCESS, str(res2.error)
            assert buf2.host_view()[: buf2.size].tobytes() == oracle["app-a"][0]
            assert ev.eviction_stats()["restages"] >= 1
        finally:
            if client is not None:
                client.close()
            srv.close()

    def test_reader_fails_fast_on_tenant_errors_no_retries(self):
        # satellite (b): typed tenant errors abort the whole fetch loop
        # immediately — retrying or failing over cannot help, every replica
        # enforces the same registry
        srv, reg, addr, oracle = _tenant_server(["app-a"], lambda a, r: b"x" * 100)
        ghost = None
        try:
            ghost = _tenant_client(addr, "ghost")
            reader = TpuShuffleReader(
                ghost,
                executor_id=ghost.executor_id,
                shuffle_id=0,
                start_partition=0,
                end_partition=2,
                num_mappers=1,
                block_sizes=lambda m, r: 100,
                max_blocks_per_request=1,
                sender_of=lambda m: 1,
                replica_of=lambda p: [1],  # a "replica" that would also reject
                fetch_retries=5,
                fetch_deadline_ms=10_000,
                fetch_backoff_ms=200,
            )
            t0 = time.monotonic()
            with pytest.raises(UnknownTenantError):
                list(reader.fetch_blocks())
            assert time.monotonic() - t0 < 5  # fail-fast, not retried to deadline
            assert reader.metrics.failovers == 0
        finally:
            if ghost is not None:
                ghost.close()
            srv.close()
