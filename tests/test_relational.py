"""Tests for the device-resident relational operators (GROUP BY, hash join)."""

from dataclasses import replace

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops.exchange import make_mesh
from sparkucx_tpu.ops.relational import (
    KEY_MAX,
    AggregateSpec,
    JoinSpec,
    build_grouped_aggregate,
    build_hash_join,
    oracle_aggregate,
    oracle_join,
    run_grouped_aggregate,
)

N = 8
CAP = 128


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N)


def _keys_sh(mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P("ex")))


def _rows_sh(mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P("ex", None)))


def _agg_inputs(mesh, keys, values, nvalid):
    return _keys_sh(mesh, keys), _rows_sh(mesh, values), _keys_sh(mesh, nvalid)


def _collect_groups(fn, mesh, keys, values, nvalid):
    gk, gv, gc, ng, rt = fn(*_agg_inputs(mesh, keys, values, nvalid))
    assert np.all(np.asarray(rt) <= fn.spec.recv_capacity), "exchange overflowed"
    gk = np.asarray(gk).reshape(N, -1)
    gv = np.asarray(gv).reshape(N, gk.shape[1], -1)
    gc = np.asarray(gc).reshape(N, -1)
    ng = np.asarray(ng)
    rows = {}
    for j in range(N):
        for g in range(ng[j]):
            k = int(gk[j, g])
            assert k not in rows, "key appeared on two shards"
            rows[k] = (gv[j, g], int(gc[j, g]))
    return rows, ng


class TestGroupedAggregate:
    @pytest.fixture(scope="class")
    def fn(self, mesh):
        spec = AggregateSpec(
            num_executors=N, capacity=CAP, recv_capacity=4 * CAP,
            aggs=("sum", "min", "max"), impl="dense",
        )
        return build_grouped_aggregate(mesh, spec)

    def test_matches_oracle(self, fn, mesh, rng):
        keys = rng.integers(0, 50, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        values = rng.integers(-100, 100, size=(N * CAP, 3), dtype=np.int64).astype(np.int32)
        nvalid = np.full(N, CAP, np.int32)
        rows, ng = _collect_groups(fn, mesh, keys, values, nvalid)
        want_k, want_v, want_c = oracle_aggregate(keys, values, ("sum", "min", "max"))
        assert sorted(rows) == list(want_k)
        for k, v, c in zip(want_k, want_v, want_c):
            got_v, got_c = rows[int(k)]
            np.testing.assert_array_equal(got_v, v)
            assert got_c == c

    def test_padding_rows_excluded(self, fn, mesh, rng):
        nvalid = rng.integers(0, CAP + 1, size=N).astype(np.int32)
        nvalid[2] = 0
        keys = np.zeros(N * CAP, np.uint32)  # padding deliberately key 0
        values = np.zeros((N * CAP, 3), np.int32)
        real_k, real_v = [], []
        for j in range(N):
            ks = rng.integers(0, 20, size=nvalid[j], dtype=np.uint64).astype(np.uint32)
            vs = rng.integers(1, 10, size=(nvalid[j], 3), dtype=np.int64).astype(np.int32)
            keys[j * CAP : j * CAP + nvalid[j]] = ks
            values[j * CAP : j * CAP + nvalid[j]] = vs
            real_k.append(ks)
            real_v.append(vs)
        rows, _ = _collect_groups(fn, mesh, keys, values, nvalid)
        want_k, want_v, want_c = oracle_aggregate(
            np.concatenate(real_k), np.concatenate(real_v), ("sum", "min", "max")
        )
        assert sorted(rows) == list(want_k)
        for k, v, c in zip(want_k, want_v, want_c):
            got_v, got_c = rows[int(k)]
            np.testing.assert_array_equal(got_v, v)
            assert got_c == c

    def test_sentinel_key_is_a_real_group(self, fn, mesh, rng):
        keys = rng.integers(0, 5, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        keys[rng.choice(N * CAP, size=33, replace=False)] = KEY_MAX
        values = np.ones((N * CAP, 3), np.int32)
        nvalid = np.full(N, CAP, np.int32)
        rows, _ = _collect_groups(fn, mesh, keys, values, nvalid)
        assert rows[int(KEY_MAX)][1] == 33

    def test_count_star_no_value_columns(self, mesh, rng):
        spec = AggregateSpec(
            num_executors=N, capacity=CAP, recv_capacity=4 * CAP, aggs=(), impl="dense"
        )
        f = build_grouped_aggregate(mesh, spec)
        keys = rng.integers(0, 10, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        values = np.zeros((N * CAP, 0), np.int32)
        rows, _ = _collect_groups(f, mesh, keys, values, np.full(N, CAP, np.int32))
        want = {int(k): c for k, c in zip(*np.unique(keys, return_counts=True))}
        assert {k: c for k, (_, c) in rows.items()} == want

    def test_float_aggregation(self, mesh, rng):
        spec = AggregateSpec(
            num_executors=N, capacity=CAP, recv_capacity=4 * CAP,
            aggs=("min", "max"), dtype=np.dtype(np.float32), impl="dense",
        )
        f = build_grouped_aggregate(mesh, spec)
        keys = rng.integers(0, 16, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        values = rng.normal(size=(N * CAP, 2)).astype(np.float32)
        rows, _ = _collect_groups(f, mesh, keys, values, np.full(N, CAP, np.int32))
        want_k, want_v, _ = oracle_aggregate(keys, values, ("min", "max"))
        for k, v in zip(want_k, want_v):
            np.testing.assert_allclose(rows[int(k)][0], v, rtol=1e-6)

    def test_spec_validation(self, mesh):
        with pytest.raises(ValueError, match="unknown aggregation"):
            AggregateSpec(
                num_executors=N, capacity=8, recv_capacity=8, aggs=("median",), impl="dense"
            ).validate()
        with pytest.raises(ValueError, match="count_distinct"):
            AggregateSpec(
                num_executors=N, capacity=8, recv_capacity=8,
                aggs=("count_distinct",), impl="dense", partial=True,
            ).validate()
        with pytest.raises(ValueError, match="mesh size"):
            build_grouped_aggregate(
                mesh, AggregateSpec(num_executors=2, capacity=8, recv_capacity=8, aggs=())
            )


def _join_inputs(mesh, bk, bv, bn, pk, pv, pn):
    return (
        _keys_sh(mesh, bk), _rows_sh(mesh, bv), _keys_sh(mesh, bn),
        _keys_sh(mesh, pk), _rows_sh(mesh, pv), _keys_sh(mesh, pn),
    )


def _collect_join(fn, mesh, *args):
    ok, ob, op, cnt, rt = fn(*_join_inputs(mesh, *args))
    rt = np.asarray(rt).reshape(N, 2)
    assert np.all(rt[:, 0] <= fn.spec.build_recv_capacity), "build exchange overflowed"
    assert np.all(rt[:, 1] <= fn.spec.probe_recv_capacity), "probe exchange overflowed"
    ok = np.asarray(ok).reshape(N, -1)
    ob = np.asarray(ob).reshape(N, ok.shape[1], -1)
    op = np.asarray(op).reshape(N, ok.shape[1], -1)
    cnt = np.asarray(cnt)
    rows = []
    for j in range(N):
        n = min(int(cnt[j]), ok.shape[1])
        for i in range(n):
            rows.append((int(ok[j, i]), tuple(ob[j, i]), tuple(op[j, i])))
    return rows, cnt


def _oracle_rows(bk, bv, pk, pv):
    k, b, p = oracle_join(bk, bv, pk, pv)
    return [(int(ki), tuple(bi), tuple(pi)) for ki, bi, pi in zip(k, b, p)]


class TestHashJoin:
    @pytest.fixture(scope="class")
    def fn(self, mesh):
        spec = JoinSpec(
            num_executors=N,
            build_capacity=CAP, build_recv_capacity=4 * CAP, build_width=2,
            probe_capacity=CAP, probe_recv_capacity=4 * CAP, probe_width=1,
            out_capacity=8 * CAP, impl="dense",
        )
        return build_hash_join(mesh, spec)

    def test_many_to_many_matches_oracle(self, fn, mesh, rng):
        bk = rng.integers(0, 40, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        bv = rng.integers(0, 1000, size=(N * CAP, 2), dtype=np.int64).astype(np.int32)
        pk = rng.integers(0, 40, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        pv = rng.integers(0, 1000, size=(N * CAP, 1), dtype=np.int64).astype(np.int32)
        # cap expansion: keep matches under out_capacity by sparsifying probe
        pn = np.full(N, 16, np.int32)
        bn = np.full(N, CAP, np.int32)
        rows, cnt = _collect_join(fn, mesh, bk, bv, bn, pk, pv, pn)
        valid_p = np.concatenate([np.arange(CAP) < pn[j] for j in range(N)])
        want = _oracle_rows(bk, bv, pk[valid_p], pv[valid_p])
        assert sorted(rows) == sorted(want)
        assert cnt.sum() == len(want)

    def test_pk_fk_join(self, fn, mesh, rng):
        # unique build keys (primary key) -> every probe row matches exactly once
        bk = rng.permutation(N * CAP).astype(np.uint32)
        bv = bk[:, None].astype(np.int32) * np.array([1, 7], np.int32)
        pk = rng.integers(0, N * CAP, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        pv = rng.integers(0, 100, size=(N * CAP, 1), dtype=np.int64).astype(np.int32)
        bn = np.full(N, CAP, np.int32)
        pn = np.full(N, CAP, np.int32)
        rows, cnt = _collect_join(fn, mesh, bk, bv, bn, pk, pv, pn)
        assert cnt.sum() == N * CAP  # every probe row found its unique build row
        for k, b, _ in rows:
            assert b == (k, 7 * k)

    def test_disjoint_keys_empty_result(self, fn, mesh, rng):
        bk = rng.integers(0, 100, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        pk = rng.integers(1000, 1100, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        z2 = np.zeros((N * CAP, 2), np.int32)
        z1 = np.zeros((N * CAP, 1), np.int32)
        full = np.full(N, CAP, np.int32)
        rows, cnt = _collect_join(fn, mesh, bk, z2, full, pk, z1, full)
        assert rows == [] and cnt.sum() == 0

    def test_empty_sides(self, fn, mesh, rng):
        keys = rng.integers(0, 10, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        z2 = np.zeros((N * CAP, 2), np.int32)
        z1 = np.zeros((N * CAP, 1), np.int32)
        zero = np.zeros(N, np.int32)
        full = np.full(N, CAP, np.int32)
        rows, _ = _collect_join(fn, mesh, keys, z2, zero, keys, z1, full)
        assert rows == []
        rows, _ = _collect_join(fn, mesh, keys, z2, full, keys, z1, zero)
        assert rows == []

    def test_sentinel_probe_key_skips_build_padding(self, fn, mesh):
        # build side: ONE valid KEY_MAX row + padding; a KEY_MAX probe must
        # match exactly the valid row, never the KEY_MAX-forced padding tail.
        bk = np.zeros(N * CAP, np.uint32)
        bk[0] = KEY_MAX
        bv = np.zeros((N * CAP, 2), np.int32)
        bv[0] = (11, 22)
        bn = np.zeros(N, np.int32)
        bn[0] = 1
        pk = np.full(N * CAP, KEY_MAX, np.uint32)
        pv = np.arange(N * CAP, dtype=np.int32)[:, None]
        pn = np.ones(N, np.int32)  # one probe row per shard
        rows, cnt = _collect_join(fn, mesh, bk, bv, bn, pk, pv, pn)
        assert cnt.sum() == N  # each of the N probe rows matched the single build row
        assert all(k == int(KEY_MAX) and b == (11, 22) for k, b, _ in rows)

    def test_overflow_reported_not_silent(self, mesh, rng):
        spec = JoinSpec(
            num_executors=N,
            build_capacity=CAP, build_recv_capacity=8 * CAP, build_width=1,
            probe_capacity=CAP, probe_recv_capacity=8 * CAP, probe_width=1,
            out_capacity=4, impl="dense",  # deliberately tiny output
        )
        f = build_hash_join(mesh, spec)
        keys = np.zeros(N * CAP, np.uint32)  # all rows share one key -> (N*CAP)^2/shard
        ones = np.ones((N * CAP, 1), np.int32)
        full = np.full(N, CAP, np.int32)
        _, _, _, cnt, rt = f(*_join_inputs(mesh, keys, ones, full, keys, ones, full))
        cnt = np.asarray(cnt)
        # the owning shard reports the true total, far beyond out_capacity
        assert cnt.max() == (N * CAP) ** 2

    def test_exchange_overflow_reported(self, mesh, rng):
        # every row hashes to ONE shard whose recv buffer is far too small:
        # recv_totals must report the true routed count, not the truncation.
        spec = JoinSpec(
            num_executors=N,
            build_capacity=CAP, build_recv_capacity=CAP // 4, build_width=1,
            probe_capacity=CAP, probe_recv_capacity=8 * CAP, probe_width=1,
            out_capacity=CAP, impl="dense",
        )
        f = build_hash_join(mesh, spec)
        keys = np.full(N * CAP, 5, np.uint32)
        ones = np.ones((N * CAP, 1), np.int32)
        full = np.full(N, CAP, np.int32)
        _, _, _, _, rt = f(*_join_inputs(mesh, keys, ones, full, keys, ones, full))
        assert np.asarray(rt)[:, 0].max() == N * CAP  # true total, > recv_capacity


class TestRunGroupedAggregate:
    """Host driver with automatic hash-skew retry (run_grouped_aggregate)."""

    def test_roundtrip_vs_oracle(self, rng):
        from sparkucx_tpu.ops.exchange import make_mesh
        from sparkucx_tpu.ops.relational import (
            AggregateSpec, oracle_aggregate, run_grouped_aggregate,
        )

        n, total = 4, 3000
        keys = rng.integers(0, 50, size=total).astype(np.uint32)
        values = rng.integers(-99, 99, size=(total, 2)).astype(np.int32)
        spec = AggregateSpec(
            num_executors=n, capacity=1024, recv_capacity=1536,
            aggs=("sum", "max"), impl="dense",
        )
        gk, gv, gc = run_grouped_aggregate(make_mesh(n), spec, keys, values)
        ok, ov, oc = oracle_aggregate(keys, values, ("sum", "max"))
        assert np.array_equal(gk, ok)
        assert np.array_equal(gv, ov)
        assert np.array_equal(gc, oc)

    def test_single_hot_key_triggers_retry(self, rng):
        from sparkucx_tpu.ops.exchange import make_mesh
        from sparkucx_tpu.ops.relational import (
            AggregateSpec, oracle_aggregate, run_grouped_aggregate,
        )

        n, total = 4, 2000
        keys = np.full(total, 42, np.uint32)  # every row hashes to one shard
        values = rng.integers(0, 10, size=(total, 1)).astype(np.int32)
        spec = AggregateSpec(
            num_executors=n, capacity=512, recv_capacity=600,
            aggs=("sum",), impl="dense",
        )
        gk, gv, gc = run_grouped_aggregate(make_mesh(n), spec, keys, values)
        assert gk.tolist() == [42]
        assert gv[0, 0] == values.sum() and gc[0] == total


class TestFilterPushdown:
    """with_filter / with_filters: WHERE below the exchange, on device."""

    def test_aggregate_scattered_mask_vs_masked_oracle(self, mesh, rng):
        spec = AggregateSpec(
            num_executors=N, capacity=CAP, recv_capacity=4 * CAP,
            aggs=("sum", "min"), impl="dense", with_filter=True,
        )
        fn = build_grouped_aggregate(mesh, spec)
        keys = rng.integers(0, 12, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        values = rng.integers(-100, 100, size=(N * CAP, 2)).astype(np.int32)
        nvalid = np.full(N, CAP, np.int32)
        mask = rng.random(N * CAP) < 0.4  # scattered, not a prefix
        gk, gv, gc, ng, rt = fn(
            _keys_sh(mesh, keys), _rows_sh(mesh, values), _keys_sh(mesh, nvalid),
            _keys_sh(mesh, mask),
        )
        assert int(np.asarray(rt).sum()) == int(mask.sum())
        gk = np.asarray(gk).reshape(N, -1)
        gv = np.asarray(gv).reshape(N, gk.shape[1], -1)
        gc = np.asarray(gc).reshape(N, -1)
        ng = np.asarray(ng)
        rows = [
            (int(gk[j, g]), (int(gv[j, g, 0]), int(gv[j, g, 1])), int(gc[j, g]))
            for j in range(N)
            for g in range(ng[j])
        ]
        wk, wv, wc = oracle_aggregate(keys[mask], values[mask], spec.aggs)
        assert sorted(rows) == sorted(
            (int(k), (int(v[0]), int(v[1])), int(c)) for k, v, c in zip(wk, wv, wc)
        )

    def test_all_rows_filtered_zero_groups(self, mesh, rng):
        spec = AggregateSpec(
            num_executors=N, capacity=CAP, recv_capacity=CAP,
            aggs=(), impl="dense", with_filter=True,
        )
        fn = build_grouped_aggregate(mesh, spec)
        keys = rng.integers(0, 5, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        values = np.zeros((N * CAP, 0), np.int32)
        nvalid = np.full(N, CAP, np.int32)
        mask = np.zeros(N * CAP, bool)
        _, _, _, ng, rt = fn(
            _keys_sh(mesh, keys), _rows_sh(mesh, values), _keys_sh(mesh, nvalid),
            _keys_sh(mesh, mask),
        )
        assert int(np.asarray(ng).sum()) == 0
        assert int(np.asarray(rt).sum()) == 0

    def test_filtered_join_vs_masked_oracle(self, mesh, rng):
        bcap = pcap = 32
        bkeys = rng.integers(0, 20, size=N * bcap, dtype=np.uint64).astype(np.uint32)
        pkeys = rng.integers(0, 20, size=N * pcap, dtype=np.uint64).astype(np.uint32)
        bvals = rng.integers(-50, 50, size=(N * bcap, 1)).astype(np.int32)
        pvals = rng.integers(-50, 50, size=(N * pcap, 1)).astype(np.int32)
        bmask = rng.random(N * bcap) < 0.5
        pmask = rng.random(N * pcap) < 0.5
        spec = JoinSpec(
            num_executors=N,
            build_capacity=bcap, build_recv_capacity=N * bcap, build_width=1,
            probe_capacity=pcap, probe_recv_capacity=N * pcap, probe_width=1,
            out_capacity=4 * N * pcap,
            impl="dense", with_filters=True,
        )
        fn = build_hash_join(mesh, spec)
        ok, ob, op_, oc, rt = fn(
            _keys_sh(mesh, bkeys), _rows_sh(mesh, bvals),
            _keys_sh(mesh, np.full(N, bcap, np.int32)),
            _keys_sh(mesh, pkeys), _rows_sh(mesh, pvals),
            _keys_sh(mesh, np.full(N, pcap, np.int32)),
            _keys_sh(mesh, bmask), _keys_sh(mesh, pmask),
        )
        rt = np.asarray(rt)
        assert rt[:, 0].sum() == bmask.sum() and rt[:, 1].sum() == pmask.sum()
        oc = np.asarray(oc)
        ok, ob, op_ = np.asarray(ok), np.asarray(ob), np.asarray(op_)
        got = sorted(
            (int(ok[i]), int(ob[i, 0]), int(op_[i, 0]))
            for s in range(N)
            for i in range(s * spec.out_capacity, s * spec.out_capacity + int(oc[s]))
        )
        wk, wb, wp = oracle_join(bkeys[bmask], bvals[bmask], pkeys[pmask], pvals[pmask])
        assert got == sorted(zip(wk.tolist(), wb[:, 0].tolist(), wp[:, 0].tolist()))

    def test_driver_with_filter_and_mismatch_raise(self, mesh, rng):
        spec = AggregateSpec(
            num_executors=N, capacity=CAP, recv_capacity=4 * CAP,
            aggs=("sum",), impl="dense", with_filter=True,
        )
        total = 500
        keys = rng.integers(0, 10, size=total, dtype=np.uint64).astype(np.uint32)
        values = rng.integers(-100, 100, size=(total, 1)).astype(np.int32)
        mask = rng.random(total) < 0.3
        gk, gv, gc = run_grouped_aggregate(mesh, spec, keys, values, mask=mask)
        wk, wv, wc = oracle_aggregate(keys[mask], values[mask], spec.aggs)
        assert np.array_equal(gk, wk) and np.array_equal(gv, wv) and np.array_equal(gc, wc)
        # signature mismatches fail with a clear message, not a pjit error
        with pytest.raises(ValueError, match="with_filter"):
            run_grouped_aggregate(mesh, spec, keys, values)
        with pytest.raises(ValueError, match="with_filter"):
            run_grouped_aggregate(
                mesh, replace(spec, with_filter=False), keys, values, mask=mask
            )


class TestLeftOuterJoin:
    def test_left_outer_vs_oracle(self, mesh, rng):
        bkeys = rng.integers(0, 30, size=60, dtype=np.uint64).astype(np.uint32)
        pkeys = rng.integers(0, 60, size=200, dtype=np.uint64).astype(np.uint32)
        bvals = rng.integers(1, 50, size=(60, 2)).astype(np.int32)
        pvals = rng.integers(1, 50, size=(200, 1)).astype(np.int32)
        from sparkucx_tpu.ops.relational import run_hash_join

        jk, jb, jp, jm = run_hash_join(
            mesh, bkeys, bvals, pkeys, pvals, impl="dense", join_type="left_outer"
        )
        wk, wb, wp, wm = oracle_join(bkeys, bvals, pkeys, pvals, join_type="left_outer")
        got = sorted(
            (int(k), tuple(b.tolist()), tuple(p.tolist()), bool(m))
            for k, b, p, m in zip(jk, jb, jp, jm)
        )
        want = sorted(
            (int(k), tuple(b.tolist()), tuple(p.tolist()), bool(m))
            for k, b, p, m in zip(wk, wb, wp, wm)
        )
        assert got == want
        assert not np.asarray(jm).all()  # some rows really were null-extended

    def test_empty_build_side_all_null_extended(self, mesh, rng):
        from sparkucx_tpu.ops.relational import run_hash_join

        pkeys = rng.integers(0, 9, size=50, dtype=np.uint64).astype(np.uint32)
        pvals = rng.integers(1, 9, size=(50, 1)).astype(np.int32)
        jk, jb, jp, jm = run_hash_join(
            mesh,
            np.zeros(0, np.uint32), np.zeros((0, 1), np.int32),
            pkeys, pvals, impl="dense", join_type="left_outer",
        )
        assert len(jk) == 50 and not jm.any()
        assert (jb == 0).all()
        assert sorted(jk.tolist()) == sorted(pkeys.tolist())

    def test_inner_unchanged_by_default(self, mesh, rng):
        # join_type defaults to inner: no matched array, unmatched probes dropped
        from sparkucx_tpu.ops.relational import run_hash_join

        bkeys = np.array([1, 2], np.uint32)
        bvals = np.array([[10], [20]], np.int32)
        pkeys = np.array([2, 3, 2], np.uint32)
        pvals = np.array([[7], [8], [9]], np.int32)
        jk, jb, jp = run_hash_join(mesh, bkeys, bvals, pkeys, pvals, impl="dense")
        assert sorted(jk.tolist()) == [2, 2]

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="join_type"):
            JoinSpec(
                num_executors=N,
                build_capacity=8, build_recv_capacity=8, build_width=1,
                probe_capacity=8, probe_recv_capacity=8, probe_width=1,
                out_capacity=8, impl="dense", join_type="cross",
            ).validate()


class TestPartialAggregate:
    """Map-side partial aggregation below the exchange (spec.partial) —
    Spark's HashAggregateExec(partial); results must be bit-identical to the
    unfused path for integer dtypes."""

    def test_bit_equality_with_unfused_fuzz(self, mesh, rng):
        from sparkucx_tpu.ops.relational import run_grouped_aggregate

        for trial in range(4):
            total = int(rng.integers(100, 2500))
            nkeys = int(rng.integers(1, 60))
            keys = rng.integers(0, nkeys, size=total).astype(np.uint32)
            values = rng.integers(-1000, 1000, size=(total, 3)).astype(np.int32)
            spec = AggregateSpec(
                num_executors=N, capacity=-(-total // N) + 8,
                recv_capacity=4 * max(32, -(-total // N)),
                aggs=("sum", "min", "max"), impl="dense",
            )
            fused = run_grouped_aggregate(mesh, replace(spec, partial=True), keys, values)
            plain = run_grouped_aggregate(mesh, spec, keys, values)
            for f, p in zip(fused, plain):
                np.testing.assert_array_equal(f, p)

    def test_hot_key_sends_one_partial_per_shard(self, mesh, rng):
        """The skew-mitigation property: a single hot key exchanges at most
        one partial row per shard, so recv_totals stays at N even for
        millions of raw rows."""
        spec = AggregateSpec(
            num_executors=N, capacity=CAP, recv_capacity=2 * N,
            aggs=("sum",), impl="dense", partial=True,
        )
        fn = build_grouped_aggregate(mesh, spec)
        keys = np.full(N * CAP, 99, np.uint32)  # one hot key everywhere
        values = np.ones((N * CAP, 1), np.int32)
        nvalid = np.full(N, CAP, np.int32)
        gk, gv, gc, ng, rt = fn(*_agg_inputs(mesh, keys, values, nvalid))
        assert int(np.asarray(rt).sum()) == N  # one partial per sender
        rows, _ = _collect_groups_raw(gk, gv, gc, ng)
        assert rows == {99: ([N * CAP], N * CAP)}

    def test_partial_with_filter_mask(self, mesh, rng):
        """Scattered WHERE masks compose with the partial path (the local
        sort must keep valid sentinel-keyed rows ahead of masked ones)."""
        spec = AggregateSpec(
            num_executors=N, capacity=CAP, recv_capacity=4 * CAP,
            aggs=("sum", "max"), impl="dense", with_filter=True, partial=True,
        )
        fn = build_grouped_aggregate(mesh, spec)
        keys = rng.integers(0, 10, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        keys[rng.choice(N * CAP, size=17, replace=False)] = KEY_MAX
        values = rng.integers(-50, 50, size=(N * CAP, 2)).astype(np.int32)
        nvalid = np.full(N, CAP, np.int32)
        mask = rng.random(N * CAP) < 0.5
        gk, gv, gc, ng, rt = fn(
            _keys_sh(mesh, keys), _rows_sh(mesh, values), _keys_sh(mesh, nvalid),
            _keys_sh(mesh, mask),
        )
        rows, _ = _collect_groups_raw(gk, gv, gc, ng)
        wk, wv, wc = oracle_aggregate(keys[mask], values[mask], spec.aggs)
        assert sorted(rows) == list(wk)
        for k, v, c in zip(wk, wv, wc):
            got_v, got_c = rows[int(k)]
            np.testing.assert_array_equal(got_v, v)
            assert got_c == c

    def test_float_partials_compose(self, mesh, rng):
        """min/max float partials compose exactly (no reassociation), and the
        bitcast count lane survives a float dtype."""
        spec = AggregateSpec(
            num_executors=N, capacity=CAP, recv_capacity=4 * CAP,
            aggs=("min", "max"), dtype=np.dtype(np.float32),
            impl="dense", partial=True,
        )
        fn = build_grouped_aggregate(mesh, spec)
        keys = rng.integers(0, 16, size=N * CAP, dtype=np.uint64).astype(np.uint32)
        values = rng.normal(size=(N * CAP, 2)).astype(np.float32)
        rows, _ = _collect_groups_raw(
            *fn(*_agg_inputs(mesh, keys, values, np.full(N, CAP, np.int32)))[:4]
        )
        wk, wv, wc = oracle_aggregate(keys, values, spec.aggs)
        for k, v, c in zip(wk, wv, wc):
            got_v, got_c = rows[int(k)]
            np.testing.assert_array_equal(np.asarray(got_v, np.float32), v)
            assert got_c == c  # counts rode the bitcast lane exactly


def _collect_groups_raw(gk, gv, gc, ng, *_):
    """_collect_groups without the fn call — for tests that already ran it."""
    gk = np.asarray(gk).reshape(N, -1)
    gv = np.asarray(gv).reshape(N, gk.shape[1], -1)
    gc = np.asarray(gc).reshape(N, -1)
    ng = np.asarray(ng)
    rows = {}
    for j in range(N):
        for g in range(ng[j]):
            k = int(gk[j, g])
            assert k not in rows, "key appeared on two shards"
            rows[k] = (list(gv[j, g]), int(gc[j, g]))
    return rows, ng


class TestAvgCountDistinct:
    def test_avg_fused_vs_oracle(self, mesh, rng):
        from sparkucx_tpu.ops.relational import run_grouped_aggregate

        total = 3000
        keys = rng.integers(0, 40, size=total).astype(np.uint32)
        values = rng.integers(-500, 500, size=(total, 2)).astype(np.int32)
        spec = AggregateSpec(
            num_executors=N, capacity=512, recv_capacity=1024,
            aggs=("avg", "sum"), impl="dense",
        )
        gk, gv, gc = run_grouped_aggregate(mesh, spec, keys, values)
        wk, wv, wc = oracle_aggregate(keys, values, spec.aggs)
        assert gv.dtype == np.float64 and wv.dtype == np.float64
        np.testing.assert_array_equal(gk, wk)
        np.testing.assert_array_equal(gv, wv)  # exact: int sums / int counts
        np.testing.assert_array_equal(gc, wc)

    def test_avg_composes_with_partial(self, mesh, rng):
        from sparkucx_tpu.ops.relational import run_grouped_aggregate

        total = 2000
        keys = rng.integers(0, 25, size=total).astype(np.uint32)
        values = rng.integers(-99, 99, size=(total, 1)).astype(np.int32)
        spec = AggregateSpec(
            num_executors=N, capacity=512, recv_capacity=1024,
            aggs=("avg",), impl="dense",
        )
        fused = run_grouped_aggregate(mesh, replace(spec, partial=True), keys, values)
        plain = run_grouped_aggregate(mesh, spec, keys, values)
        for f, p in zip(fused, plain):
            np.testing.assert_array_equal(f, p)

    def test_count_distinct_vs_oracle(self, mesh, rng):
        from sparkucx_tpu.ops.relational import run_grouped_aggregate

        total = 2500
        keys = rng.integers(0, 30, size=total).astype(np.uint32)
        # few distinct values -> heavy duplication inside groups
        values = rng.integers(0, 12, size=(total, 2)).astype(np.int32)
        values[:, 1] = rng.integers(-3, 3, size=total)
        spec = AggregateSpec(
            num_executors=N, capacity=512, recv_capacity=1024,
            aggs=("count_distinct", "count_distinct"), impl="dense",
        )
        gk, gv, gc = run_grouped_aggregate(mesh, spec, keys, values)
        wk, wv, wc = oracle_aggregate(keys, values, spec.aggs)
        np.testing.assert_array_equal(gk, wk)
        np.testing.assert_array_equal(gv, wv)
        np.testing.assert_array_equal(gc, wc)

    def test_count_distinct_sentinel_and_mask(self, mesh, rng):
        """count_distinct with scattered masks and KEY_MAX keys (the lexsort
        numbering must stay aligned with the main segment numbering)."""
        from sparkucx_tpu.ops.relational import run_grouped_aggregate

        total = 1200
        keys = rng.integers(0, 8, size=total).astype(np.uint32)
        keys[rng.choice(total, size=21, replace=False)] = KEY_MAX
        values = rng.integers(0, 5, size=(total, 1)).astype(np.int32)
        mask = rng.random(total) < 0.6
        spec = AggregateSpec(
            num_executors=N, capacity=256, recv_capacity=1024,
            aggs=("count_distinct",), impl="dense", with_filter=True,
        )
        gk, gv, gc = run_grouped_aggregate(mesh, spec, keys, values, mask=mask)
        wk, wv, wc = oracle_aggregate(keys[mask], values[mask], spec.aggs)
        np.testing.assert_array_equal(gk, wk)
        np.testing.assert_array_equal(gv, wv)
        np.testing.assert_array_equal(gc, wc)


class TestRightFullOuterJoin:
    def _check(self, mesh, rng, join_type, bkeys, bvals, pkeys, pvals):
        from sparkucx_tpu.ops.relational import run_hash_join

        jk, jb, jp, jm = run_hash_join(
            mesh, bkeys, bvals, pkeys, pvals, impl="dense", join_type=join_type
        )
        wk, wb, wp, wm = oracle_join(bkeys, bvals, pkeys, pvals, join_type=join_type)
        got = sorted(
            (int(k), tuple(b.tolist()), tuple(p.tolist()), bool(m))
            for k, b, p, m in zip(jk, jb, jp, jm)
        )
        want = sorted(
            (int(k), tuple(b.tolist()), tuple(p.tolist()), bool(m))
            for k, b, p, m in zip(wk, wb, wp, wm)
        )
        assert got == want
        return jm

    def test_right_outer_vs_oracle(self, mesh, rng):
        bkeys = rng.integers(0, 60, size=80, dtype=np.uint64).astype(np.uint32)
        pkeys = rng.integers(0, 30, size=150, dtype=np.uint64).astype(np.uint32)
        bvals = rng.integers(1, 50, size=(80, 2)).astype(np.int32)
        pvals = rng.integers(1, 50, size=(150, 1)).astype(np.int32)
        jm = self._check(mesh, rng, "right_outer", bkeys, bvals, pkeys, pvals)
        assert not jm.all()  # some build rows really were unmatched

    def test_full_outer_vs_oracle(self, mesh, rng):
        # disjoint key halves guarantee null-extensions on BOTH sides
        bkeys = rng.integers(0, 40, size=70, dtype=np.uint64).astype(np.uint32)
        pkeys = rng.integers(20, 60, size=90, dtype=np.uint64).astype(np.uint32)
        bvals = rng.integers(1, 9, size=(70, 1)).astype(np.int32)
        pvals = rng.integers(1, 9, size=(90, 2)).astype(np.int32)
        jm = self._check(mesh, rng, "full_outer", bkeys, bvals, pkeys, pvals)
        assert not jm.all()

    def test_full_outer_preserves_every_row(self, mesh, rng):
        """Row-conservation law: inner matches + probe-unmatched +
        build-unmatched = full outer output."""
        from sparkucx_tpu.ops.relational import run_hash_join

        bkeys = rng.integers(0, 20, size=50, dtype=np.uint64).astype(np.uint32)
        pkeys = rng.integers(10, 30, size=60, dtype=np.uint64).astype(np.uint32)
        bvals = rng.integers(1, 9, size=(50, 1)).astype(np.int32)
        pvals = rng.integers(1, 9, size=(60, 1)).astype(np.int32)
        inner = run_hash_join(mesh, bkeys, bvals, pkeys, pvals, impl="dense")
        full = run_hash_join(
            mesh, bkeys, bvals, pkeys, pvals, impl="dense", join_type="full_outer"
        )
        p_unmatched = (~np.isin(pkeys, bkeys)).sum()
        b_unmatched = (~np.isin(bkeys, pkeys)).sum()
        assert len(full[0]) == len(inner[0]) + p_unmatched + b_unmatched

    def test_right_outer_empty_probe_side(self, mesh, rng):
        from sparkucx_tpu.ops.relational import run_hash_join

        bkeys = rng.integers(0, 9, size=40, dtype=np.uint64).astype(np.uint32)
        bvals = rng.integers(1, 9, size=(40, 2)).astype(np.int32)
        jk, jb, jp, jm = run_hash_join(
            mesh,
            bkeys, bvals,
            np.zeros(0, np.uint32), np.zeros((0, 1), np.int32),
            impl="dense", join_type="right_outer",
        )
        assert len(jk) == 40 and not jm.any()
        assert (jp == 0).all()
        assert sorted(jk.tolist()) == sorted(bkeys.tolist())

    def test_sentinel_build_key_full_outer(self, mesh):
        """Valid KEY_MAX build rows must null-extend exactly once each, never
        be confused with probe-side padding."""
        from sparkucx_tpu.ops.relational import run_hash_join

        bkeys = np.array([KEY_MAX, 3], np.uint32)
        bvals = np.array([[111], [333]], np.int32)
        pkeys = np.array([3, 4], np.uint32)
        pvals = np.array([[30], [40]], np.int32)
        jk, jb, jp, jm = run_hash_join(
            mesh, bkeys, bvals, pkeys, pvals, impl="dense", join_type="full_outer"
        )
        rows = sorted(zip(jk.tolist(), jb[:, 0].tolist(), jp[:, 0].tolist(), jm.tolist()))
        assert rows == [
            (3, 333, 30, True),          # the inner match
            (4, 0, 40, False),           # probe-side null extension
            (int(KEY_MAX), 111, 0, False),  # build-side null extension
        ]


class TestSemiAntiJoin:
    def test_semi_and_anti_partition_the_probe(self, mesh, rng):
        """Semi + anti outputs together must be exactly the probe rows, split
        by match existence — EXISTS / NOT EXISTS (TPC-H q4/q21/q22)."""
        from sparkucx_tpu.ops.relational import run_hash_join

        bkeys = rng.integers(0, 25, size=40, dtype=np.uint64).astype(np.uint32)
        pkeys = rng.integers(0, 50, size=150, dtype=np.uint64).astype(np.uint32)
        bvals = rng.integers(1, 9, size=(40, 1)).astype(np.int32)
        pvals = rng.integers(1, 9, size=(150, 2)).astype(np.int32)

        semi = run_hash_join(
            mesh, bkeys, bvals, pkeys, pvals, impl="dense", join_type="left_semi"
        )
        anti = run_hash_join(
            mesh, bkeys, bvals, pkeys, pvals, impl="dense", join_type="left_anti"
        )
        for got, jt in ((semi, "left_semi"), (anti, "left_anti")):
            wk, wb, wp = oracle_join(bkeys, bvals, pkeys, pvals, join_type=jt)
            assert sorted(
                (int(k), tuple(p.tolist())) for k, p in zip(got[0], got[2])
            ) == sorted((int(k), tuple(p.tolist())) for k, p in zip(wk, wp)), jt
            assert (got[1] == 0).all(), f"{jt} must zero build lanes"
        # the partition property
        exists = np.isin(pkeys, bkeys)
        assert len(semi[0]) == exists.sum()
        assert len(anti[0]) == (~exists).sum()
        assert len(semi[0]) + len(anti[0]) == len(pkeys)

    def test_semi_emits_each_probe_row_once(self, mesh, rng):
        # heavy build duplication must not multiply semi output
        from sparkucx_tpu.ops.relational import run_hash_join

        bkeys = np.full(90, 7, np.uint32)  # 90 build rows, one key
        bvals = np.arange(90, dtype=np.int32)[:, None]
        pkeys = np.array([7, 7, 8], np.uint32)
        pvals = np.array([[1], [2], [3]], np.int32)
        jk, jb, jp = run_hash_join(
            mesh, bkeys, bvals, pkeys, pvals, impl="dense", join_type="left_semi"
        )
        assert sorted(jp[:, 0].tolist()) == [1, 2]  # the two key-7 probe rows, once each


class TestAggregateSpecFromConf:
    """conf.partial_aggregation enters plans through from_conf — the
    partialAggregation Spark key must actually change the compiled spec."""

    def test_conf_defaults_flow_into_spec(self):
        from sparkucx_tpu.config import TpuShuffleConf

        conf = TpuShuffleConf(num_executors=4)
        spec = AggregateSpec.from_conf(conf, capacity=8, recv_capacity=32, aggs=("sum",))
        assert spec.partial is True  # the documented on-by-default
        assert spec.num_executors == 4
        assert spec.axis_name == conf.mesh_axis_name
        off = AggregateSpec.from_conf(
            TpuShuffleConf(partial_aggregation=False),
            num_executors=2, capacity=8, recv_capacity=32, aggs=("sum",),
        )
        assert off.partial is False
        spec.resolve_impl("cpu").validate()
        off.resolve_impl("cpu").validate()

    def test_explicit_kwargs_win(self):
        from sparkucx_tpu.config import TpuShuffleConf

        spec = AggregateSpec.from_conf(
            TpuShuffleConf(), num_executors=2, capacity=8, recv_capacity=32,
            aggs=("sum",), partial=False,
        )
        assert spec.partial is False

    def test_count_distinct_auto_disables_partial(self):
        from sparkucx_tpu.config import TpuShuffleConf

        spec = AggregateSpec.from_conf(
            TpuShuffleConf(), num_executors=2, capacity=8, recv_capacity=32,
            aggs=("sum", "count_distinct"),
        )
        assert spec.partial is False
        # must not raise despite conf partial_aggregation=True
        spec.resolve_impl("cpu").validate()
