"""FAST-scheduled ring exchange (ops/ici_exchange.py; conf exchange.impl).

Four layers of pinning, mirroring the skew suite's structure:

* schedule model — pure-python property tests over ``ring_schedule`` /
  ``simulate_ring``: every (src, dst, chunk) window delivered exactly once,
  at most one window per link direction per superstep, chunk-major FAST
  interleaving, antipodal alternation, pow2 chunk clamping;
* lowering bit-equality — the scheduled-permute exchange (flat, hierarchical,
  and the fused scatter+exchange send side) must produce byte-for-byte the
  stock collective's receive state on the 8-way CPU mesh;
* topology probe — slice_index-derived hop classification and mesh
  factorization with stand-in device objects (the pure-python fallback);
* cluster bit-equality — ``exchange.impl=pallas`` through the full
  TpuShuffleCluster must match the stock default across host_recv_modes and
  quota planning, plus a true two-process SPMD lockstep run.
"""

import os
import subprocess
import sys
from types import SimpleNamespace

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.ops.exchange import ExchangeSpec, build_exchange, make_mesh
from sparkucx_tpu.ops.hierarchy import (
    build_hierarchical_exchange,
    device_slice_ids,
    hop_kinds,
    hop_schedule,
    make_hierarchical_mesh,
    probe_topology,
)
from sparkucx_tpu.ops.ici_exchange import (
    DEFAULT_CHUNKS_PER_DEST,
    HierarchicalSchedule,
    RingSchedule,
    build_fused_ici_exchange,
    build_ici_exchange,
    resolve_exchange_impl,
    resolve_ici_lowering,
    resolve_schedule_lowering,
    ring_schedule,
    schedule_chunks,
    simulate_ring,
    step_occupancy,
)
from sparkucx_tpu.ops.pallas_kernels import ring_axis_layout
from sparkucx_tpu.transport.tpu import TpuShuffleCluster

N = 8
LANE = 32
ROW_BYTES = LANE * 4


# ----------------------------------------------------------------------
# schedule model (pure python, no mesh)


class TestRingSchedule:
    @pytest.mark.parametrize("dim", [2, 3, 4, 5, 8])
    @pytest.mark.parametrize("chunks", [1, 2, 4])
    def test_exactly_once_and_link_cap(self, dim, chunks):
        sched = ring_schedule(dim, chunks)
        deliveries, link_load = simulate_ring(sched)
        # every remote (src, dst, chunk) window exactly once, nothing local
        for src in range(dim):
            for dst in range(dim):
                for c in range(chunks):
                    want = 0 if src == dst else 1
                    assert deliveries.get((src, dst, c), 0) == want, (src, dst, c)
        # <= 1 window per device per ring direction per superstep
        assert all(v <= 1 for v in link_load.values())

    @pytest.mark.parametrize("dim", [3, 4, 8])
    def test_chunk_major_interleaving(self, dim):
        """FAST hot-lane interleaving: chunk 0 of EVERY destination is
        scheduled before chunk 1 of any — per ring direction the chunk
        sequence is non-decreasing."""
        sched = ring_schedule(dim, 4)
        for direction in (1, -1):
            seq = [it.chunk for it in sched.items() if it.direction == direction]
            assert seq == sorted(seq)

    @pytest.mark.parametrize("dim", [2, 4, 8])
    def test_antipodal_alternates_directions(self, dim):
        """The half-way offset has no short way; its chunks split across both
        rings by parity so neither direction carries the whole hot lane."""
        sched = ring_schedule(dim, 4)
        anti = [it for it in sched.items() if 2 * it.offset == dim]
        assert anti, "even dims have an antipodal offset"
        for it in anti:
            assert it.direction == (1 if it.chunk % 2 == 0 else -1)

    def test_step_count_and_occupancy(self):
        # n=8, 2 chunks: 14 items split 8 (+) / 6 (-) by short-way -> 8 steps
        sched = ring_schedule(8, 2)
        assert sched.num_steps == max(
            sum(1 for it in sched.items() if it.direction == 1),
            sum(1 for it in sched.items() if it.direction == -1),
        )
        occ = step_occupancy(sched)
        assert sum(b for b, _ in occ) == 2 * (8 - 1)
        assert all(b + i == 2 for b, i in occ)

    def test_rejects_bad_dims(self):
        with pytest.raises(ValueError, match="dim"):
            ring_schedule(1)
        with pytest.raises(ValueError, match="chunks_per_dest"):
            ring_schedule(4, 0)


class TestScheduleChunks:
    def test_pow2_divisor_clamp(self):
        assert schedule_chunks(16, 3) == 4  # pow2 ceil of 3
        assert schedule_chunks(16, 64) == 16  # capped at the group
        assert schedule_chunks(12, 8) == 4  # largest pow2 divisor of 12
        assert schedule_chunks(8, 1) == 1
        assert schedule_chunks(7, 4) == 1  # odd groups stay unchunked

    def test_rejects_nonpositive_group(self):
        with pytest.raises(ValueError, match="group_rows"):
            schedule_chunks(0, 2)


class TestResolvers:
    def test_exchange_impl_matrix(self):
        assert resolve_exchange_impl("stock", "tpu", 8) == "stock"
        assert resolve_exchange_impl("pallas", "cpu", 8) == "pallas"
        assert resolve_exchange_impl("auto", "tpu", 8) == "pallas"
        assert resolve_exchange_impl("auto", "tpu", 1) == "stock"
        assert resolve_exchange_impl("auto", "cpu", 8) == "stock"
        with pytest.raises(ValueError, match="exchange impl"):
            resolve_exchange_impl("bogus", "cpu", 8)

    def test_lowering_matrix(self):
        assert resolve_ici_lowering("auto", "tpu") == "dma"
        assert resolve_ici_lowering("auto", "cpu") == "xla"
        assert resolve_ici_lowering("interpret", "tpu") == "interpret"
        with pytest.raises(ValueError, match="lowering"):
            resolve_ici_lowering("bogus", "cpu")

    def test_fabric_guard_forces_xla_for_dcn(self):
        """Remote DMA cannot cross slices: any dcn-classified ring must drop
        from the dma tier to scheduled permutes; ici rings keep their tier."""
        assert resolve_schedule_lowering("dma", "dcn") == "xla"
        assert resolve_schedule_lowering("dma", "ici") == "dma"
        assert resolve_schedule_lowering("xla", "dcn") == "xla"
        assert resolve_schedule_lowering("interpret", "dcn") == "interpret"


class TestRingAxisLayout:
    """Ring-position -> LOGICAL device id mapping of the Pallas remote-DMA
    tier: on a (dcn, ici) mesh the ICI phase's ring position c is logical
    device ``s * C + c``, NOT c — the wrong-device-write bug class the kernel
    rebases away."""

    def test_flat_mesh_identity(self):
        stride, others = ring_axis_layout((("ex", 8),), "ex")
        assert (stride, others) == (1, ())

    def test_hierarchical_ici_axis(self):
        stride, others = ring_axis_layout((("dcn", 2), ("ici", 4)), "ici")
        assert stride == 1
        assert others == (("dcn", 4),)
        # slice s, ring position p -> global logical id s*4 + p
        for s in range(2):
            for p in range(4):
                assert s * 4 + p * stride == s * 4 + p

    def test_hierarchical_dcn_axis(self):
        stride, others = ring_axis_layout((("dcn", 2), ("ici", 4)), "dcn")
        assert stride == 4
        assert others == (("ici", 1),)

    def test_three_axis_mesh(self):
        stride, others = ring_axis_layout(
            (("a", 2), ("b", 3), ("c", 5)), "b"
        )
        assert stride == 5
        assert others == (("a", 15), ("c", 1))

    def test_unknown_axis_raises(self):
        with pytest.raises(ValueError, match="ring axis"):
            ring_axis_layout((("dcn", 2), ("ici", 4)), "ex")


# ----------------------------------------------------------------------
# topology probe (stand-in device objects; the pure-python fallback path)


class _Dev:
    def __init__(self, slice_index=None):
        if slice_index is not None:
            self.slice_index = slice_index


class TestTopologyProbe:
    def test_slice_ids_absent(self):
        assert device_slice_ids([_Dev(), _Dev()]) is None
        assert device_slice_ids([_Dev(0), _Dev()]) is None  # partial = none

    def test_flat_fallback(self):
        devs = [_Dev() for _ in range(4)]
        assert probe_topology(devs)[:2] == (1, 4)
        assert hop_kinds(devs)[0, 1] == "ici"
        assert hop_kinds(devs)[2, 2] == "local"

    def test_groups_interleaved_enumeration(self):
        """jax.devices() order is NOT trusted: devices are regrouped by
        slice_index so each mesh row is one physical slice."""
        devs = [_Dev(0), _Dev(1), _Dev(0), _Dev(1)]
        s, c, ordered = probe_topology(devs)
        assert (s, c) == (2, 2)
        assert [d.slice_index for d in ordered] == [0, 0, 1, 1]

    def test_ragged_slices_raise(self):
        with pytest.raises(ValueError, match="ragged"):
            probe_topology([_Dev(0), _Dev(0), _Dev(1)])

    def test_hop_kinds_cross_slice(self):
        devs = [_Dev(0), _Dev(0), _Dev(1), _Dev(1)]
        kinds = hop_kinds(devs)
        assert kinds[0, 1] == "ici" and kinds[2, 3] == "ici"
        assert kinds[0, 2] == "dcn" and kinds[3, 0] == "dcn"

    def test_mesh_incompatible_factorization_raises(self):
        """A request whose ici rows would mix physical slices (remote DMA
        cannot reach across them) is rejected: chips_per_slice=4 does not
        divide the physical 2."""
        devs = [_Dev(0), _Dev(0), _Dev(1), _Dev(1)]
        with pytest.raises(ValueError, match="topology"):
            make_hierarchical_mesh(1, 4, devices=devs)

    def test_mesh_compatible_refactorization_allowed(self):
        """Splitting a physical slice axis differently is fine as long as
        every ici row stays inside one slice — 2x2 hardware as a 4x1 mesh
        (rows slice-major, extra same-slice hops ride the DCN path)."""
        devs = [_Dev(0), _Dev(1), _Dev(0), _Dev(1)]
        mesh = make_hierarchical_mesh(4, 1, devices=devs)
        rows = [d.slice_index for d in mesh.devices.reshape(-1)]
        assert rows == [0, 0, 1, 1]  # regrouped slice-major before reshape


class TestHopSchedule:
    def test_flat_mesh_single_ring(self):
        sched = hop_schedule(make_mesh(4), chunks_per_dest=2, slot_rows=16)
        assert isinstance(sched, RingSchedule)
        assert (sched.dim, sched.chunks, sched.kind) == (4, 2, "ici")

    def test_hierarchical_mesh_distinct_fabrics(self):
        mesh = make_hierarchical_mesh(2, 4)
        sched = hop_schedule(mesh, chunks_per_dest=2, slot_rows=16)
        assert isinstance(sched, HierarchicalSchedule)
        assert sched.ici is not None and sched.ici.dim == 4
        assert sched.ici.kind == "ici"
        assert sched.dcn is not None and sched.dcn.dim == 2
        assert sched.dcn.kind == "dcn"

    def test_chunks_clamped_per_phase(self):
        # ici phase group = S*slot = 2*6 = 12 rows -> pow2 divisor 4
        mesh = make_hierarchical_mesh(2, 4)
        sched = hop_schedule(mesh, chunks_per_dest=8, slot_rows=6)
        assert sched.ici.chunks == 4
        assert sched.dcn.chunks == 8  # dcn group = C*slot = 24 -> 8 divides

    def test_flat_mesh_spanning_slices_is_dcn(self):
        """A flat ring over a multi-slice deployment: some source crosses DCN
        at every offset, so the whole schedule is classified 'dcn' and the
        lowering guard keeps it off the remote-DMA tier."""
        devs = [_Dev(0), _Dev(0), _Dev(1), _Dev(1)]
        mesh = SimpleNamespace(
            axis_names=("ex",), shape={"ex": 4},
            devices=np.array(devs, dtype=object),
        )
        sched = hop_schedule(mesh, chunks_per_dest=2, slot_rows=16)
        assert isinstance(sched, RingSchedule)
        assert sched.kind == "dcn"

    def test_hierarchical_mixed_rows_conservative(self):
        """A hand-built (dcn, ici) mesh whose ici rows mix slices: the ici
        phase is conservatively classified 'dcn' (remote DMA can't serve
        those hops)."""
        devs = [_Dev(0), _Dev(1), _Dev(0), _Dev(1)]  # rows mix slices
        mesh = SimpleNamespace(
            axis_names=("dcn", "ici"), shape={"dcn": 2, "ici": 2},
            devices=np.array(devs, dtype=object).reshape(2, 2),
        )
        sched = hop_schedule(mesh, chunks_per_dest=1, slot_rows=8)
        assert sched.ici is not None and sched.ici.kind == "dcn"

    def test_hierarchical_slice_pure_rows_stay_ici(self):
        devs = [_Dev(0), _Dev(0), _Dev(1), _Dev(1)]  # rows slice-pure
        mesh = SimpleNamespace(
            axis_names=("dcn", "ici"), shape={"dcn": 2, "ici": 2},
            devices=np.array(devs, dtype=object).reshape(2, 2),
        )
        sched = hop_schedule(mesh, chunks_per_dest=1, slot_rows=8)
        assert sched.ici is not None and sched.ici.kind == "ici"


# ----------------------------------------------------------------------
# lowering bit-equality vs the stock collective (8-way CPU mesh)


def _random_case(rng, n, slot):
    sizes = rng.integers(0, slot + 1, size=(n, n)).astype(np.int32)
    data = rng.integers(-100, 100, size=(n * n * slot, LANE), dtype=np.int32)
    return data, sizes


def _run(fn, mesh, data, sizes):
    sharding = NamedSharding(mesh, P(tuple(mesh.axis_names), None))
    d = jax.device_put(data, sharding)
    s = jax.device_put(sizes, sharding)
    recv, rs = fn(d, s)
    return np.asarray(recv), np.asarray(rs)


class TestFlatBitEquality:
    @pytest.mark.parametrize("n", [2, 4, 8])
    @pytest.mark.parametrize("chunks", [1, 2])
    def test_matches_stock(self, rng, n, chunks):
        slot = 16
        spec = ExchangeSpec(
            num_executors=n, send_rows=n * slot, recv_rows=n * slot, lane=LANE
        )
        mesh = make_mesh(n)
        stock = build_exchange(mesh, spec)
        sched = build_ici_exchange(mesh, spec, chunks_per_dest=chunks)
        assert sched.lowering == "xla"  # CPU mesh: scheduled permutes
        data, sizes = _random_case(rng, n, slot)
        recv_s, rs_s = _run(stock, mesh, data, sizes)
        recv_p, rs_p = _run(sched, mesh, data, sizes)
        np.testing.assert_array_equal(rs_s, rs_p)
        assert recv_s.tobytes() == recv_p.tobytes()

    @pytest.mark.parametrize("chunks", [1, 2])
    def test_interpret_kernel_matches_stock(self, rng, chunks):
        """The Pallas kernel BODY — barrier-free interpret discharge of the
        schedule walk, remote-copy placement, and ring-position -> logical
        device id mapping — must be bit-identical to the stock collective.
        This is the tier that actually executes ring_exchange_grid on the
        CPU mesh (the xla tier never enters the kernel)."""
        n, slot = 4, 8
        spec = ExchangeSpec(
            num_executors=n, send_rows=n * slot, recv_rows=n * slot, lane=LANE
        )
        mesh = make_mesh(n)
        stock = build_exchange(mesh, spec)
        interp = build_ici_exchange(
            mesh, spec, chunks_per_dest=chunks, lowering="interpret"
        )
        assert interp.lowering == "interpret"
        data, sizes = _random_case(rng, n, slot)
        recv_s, rs_s = _run(stock, mesh, data, sizes)
        recv_p, rs_p = _run(interp, mesh, data, sizes)
        np.testing.assert_array_equal(rs_s, rs_p)
        assert recv_s.tobytes() == recv_p.tobytes()

    def test_asymmetric_recv_rows_no_donation(self, rng):
        """send_rows != recv_rows disables donation (the build_exchange rule)
        and still compacts identically."""
        n, slot = 4, 8
        spec = ExchangeSpec(
            num_executors=n, send_rows=n * slot, recv_rows=2 * n * slot, lane=LANE
        )
        mesh = make_mesh(n)
        stock = build_exchange(mesh, spec)
        sched = build_ici_exchange(mesh, spec, chunks_per_dest=2)
        data, sizes = _random_case(rng, n, slot)
        recv_s, rs_s = _run(stock, mesh, data, sizes)
        recv_p, rs_p = _run(sched, mesh, data, sizes)
        np.testing.assert_array_equal(rs_s, rs_p)
        assert recv_s.tobytes() == recv_p.tobytes()

    def test_n1_delegates_to_stock(self):
        spec = ExchangeSpec(num_executors=1, send_rows=8, recv_rows=8, lane=LANE)
        fn = build_ici_exchange(make_mesh(1), spec)
        assert not hasattr(fn, "schedule"), "n=1 must take the stock builder"

    def test_builder_validation(self):
        spec = ExchangeSpec(num_executors=4, send_rows=32, recv_rows=32, lane=LANE)
        mesh = make_mesh(4)
        with pytest.raises(ValueError, match="mesh size"):
            build_ici_exchange(make_mesh(2), spec)
        with pytest.raises(ValueError, match="schedule dim"):
            build_ici_exchange(mesh, spec, schedule=ring_schedule(8, 1))
        with pytest.raises(ValueError, match="divide"):
            build_ici_exchange(mesh, spec, schedule=ring_schedule(4, 3))
        with pytest.raises(ValueError, match="RingSchedule"):
            build_ici_exchange(
                mesh, spec,
                schedule=HierarchicalSchedule(2, 2, ring_schedule(2), ring_schedule(2)),
            )


class TestHierarchicalBitEquality:
    @pytest.mark.parametrize("chunks", [1, 2])
    def test_matches_two_phase_stock(self, rng, chunks):
        S, C, slot = 2, 4, 8
        n = S * C
        spec = ExchangeSpec(
            num_executors=n, send_rows=n * slot, recv_rows=n * slot, lane=LANE
        )
        mesh = make_hierarchical_mesh(S, C)
        stock = build_hierarchical_exchange(mesh, spec.resolve_impl())
        sched = build_ici_exchange(mesh, spec, chunks_per_dest=chunks)
        assert isinstance(sched.schedule, HierarchicalSchedule)
        data, sizes = _random_case(rng, n, slot)
        recv_s, rs_s = _run(stock, mesh, data, sizes)
        recv_p, rs_p = _run(sched, mesh, data, sizes)
        np.testing.assert_array_equal(rs_s, rs_p)
        assert recv_s.tobytes() == recv_p.tobytes()

    def test_needs_hierarchical_schedule(self):
        S, C, slot = 2, 4, 8
        n = S * C
        spec = ExchangeSpec(
            num_executors=n, send_rows=n * slot, recv_rows=n * slot, lane=LANE
        )
        with pytest.raises(ValueError, match="Hierarchical"):
            build_ici_exchange(
                make_hierarchical_mesh(S, C), spec, schedule=ring_schedule(n, 1)
            )

    def test_user_schedule_validation(self):
        """A user-supplied HierarchicalSchedule whose chunks don't divide the
        phase transfer group must raise (not silently truncate window_rows
        and drop the tail of every transfer), mirroring the flat branch."""
        S, C, slot = 2, 4, 8  # ici group = S*slot = 16, dcn group = C*slot = 32
        n = S * C
        spec = ExchangeSpec(
            num_executors=n, send_rows=n * slot, recv_rows=n * slot, lane=LANE
        )
        mesh = make_hierarchical_mesh(S, C)

        def sched(ici, dcn, s=S, c=C):
            return HierarchicalSchedule(s, c, ici, dcn)

        good_ici = ring_schedule(C, 1, kind="ici")
        good_dcn = ring_schedule(S, 1, kind="dcn")
        with pytest.raises(ValueError, match="ici chunks"):
            build_ici_exchange(
                mesh, spec, schedule=sched(ring_schedule(C, 3, kind="ici"), good_dcn)
            )
        with pytest.raises(ValueError, match="dcn chunks"):
            build_ici_exchange(
                mesh, spec, schedule=sched(good_ici, ring_schedule(S, 3, kind="dcn"))
            )
        with pytest.raises(ValueError, match="ici schedule dim"):
            build_ici_exchange(
                mesh, spec, schedule=sched(ring_schedule(2, 1, kind="ici"), good_dcn)
            )
        with pytest.raises(ValueError, match="dcn schedule dim"):
            build_ici_exchange(
                mesh, spec, schedule=sched(good_ici, ring_schedule(4, 1, kind="dcn"))
            )
        with pytest.raises(ValueError, match="factorization"):
            build_ici_exchange(
                mesh, spec,
                schedule=HierarchicalSchedule(
                    C, S, ring_schedule(S, 1), ring_schedule(C, 1)
                ),
            )


class TestFusedSendSide:
    def test_matches_scatter_then_exchange(self, rng):
        """The fused plan (scatter + scheduled exchange, one launch) equals
        staging the blocks first and running the stock collective after."""
        n, slot = 4, 16
        send_rows = n * slot
        spec = ExchangeSpec(
            num_executors=n, send_rows=send_rows, recv_rows=send_rows, lane=LANE
        )
        mesh = make_mesh(n)
        sizes = rng.integers(1, slot + 1, size=(n, n)).astype(np.int32)
        starts = np.zeros((n, n), dtype=np.int32)
        counts = np.zeros((n, n), dtype=np.int32)
        outs = np.zeros((n, n), dtype=np.int32)
        packed = np.zeros((n * send_rows, LANE), dtype=np.int32)
        staged_ref = np.zeros((n * send_rows, LANE), dtype=np.int32)
        for i in range(n):
            off = 0
            for j in range(n):
                c = int(sizes[i, j])
                rows = rng.integers(-100, 100, size=(c, LANE), dtype=np.int32)
                packed[i * send_rows + off : i * send_rows + off + c] = rows
                staged_ref[
                    i * send_rows + j * slot : i * send_rows + j * slot + c
                ] = rows
                starts[i, j], counts[i, j], outs[i, j] = j * slot, c, off
                off += c
        fused = build_fused_ici_exchange(
            mesh, spec, n, chunks_per_dest=2, max_block_rows=slot
        )
        stock = build_exchange(mesh, spec)
        sharding = NamedSharding(mesh, P("ex", None))
        put = lambda a: jax.device_put(a, sharding)
        recv_ref, rs_ref = stock(put(staged_ref), put(sizes))
        recv_f, rs_f = fused(
            put(starts), put(counts), put(outs), put(packed),
            put(np.zeros((n * send_rows, LANE), dtype=np.int32)), put(sizes),
        )
        np.testing.assert_array_equal(np.asarray(rs_ref), np.asarray(rs_f))
        assert np.asarray(recv_ref).tobytes() == np.asarray(recv_f).tobytes()

    def test_rejects_hierarchical_mesh(self):
        spec = ExchangeSpec(num_executors=8, send_rows=64, recv_rows=64, lane=LANE)
        with pytest.raises(ValueError, match="flat"):
            build_fused_ici_exchange(make_hierarchical_mesh(2, 4), spec, 4)


# ----------------------------------------------------------------------
# conf plumbing


class TestConf:
    def test_from_spark_conf(self):
        conf = TpuShuffleConf.from_spark_conf(
            {"spark.shuffle.tpu.exchange.impl": "pallas"}
        )
        assert conf.exchange_impl == "pallas"

    def test_default_is_stock(self):
        assert TpuShuffleConf().exchange_impl == "stock"

    def test_validate_rejects_unknown(self):
        conf = TpuShuffleConf(exchange_impl="bogus")
        with pytest.raises(ValueError, match="exchange_impl"):
            conf.validate()


# ----------------------------------------------------------------------
# cluster bit-equality: exchange.impl=pallas through the full transport
# (the skew suite's idiom: seeded skewed writes, byte-compared receive state)

N_EXEC = 4


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


def _write_skewed(cluster, shuffle_id, M, R, seed=77):
    meta = cluster.create_shuffle(shuffle_id, M, R)
    rng = np.random.default_rng(seed)
    oracle = {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(shuffle_id, m)
        for r in range(R):
            size = int(rng.integers(2000, 3000)) if r == 0 else int(rng.integers(1, 300))
            payload = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    return meta, oracle


def _fetch_all(cluster, meta, shuffle_id, M, R, oracle):
    for r in range(R):
        consumer = meta.owner_of_reduce(r)
        t = cluster.transport(consumer)
        bufs = [_buf(8192) for _ in range(M)]
        reqs = t.fetch_blocks_by_block_ids(
            consumer, [ShuffleBlockId(shuffle_id, m, r) for m in range(M)],
            bufs, [None] * M,
        )
        for m in range(M):
            res = reqs[m].wait(5)
            assert res.status == OperationStatus.SUCCESS, str(res.error)
            assert bufs[m].host_view()[: bufs[m].size].tobytes() == oracle[(m, r)]


def _conf(impl, quota=0, mode="array", **kw):
    return TpuShuffleConf(
        staging_capacity_per_executor=N_EXEC * 4096,
        block_alignment=128,
        num_executors=N_EXEC,
        host_recv_mode=mode,
        slot_quota_rows=quota,
        exchange_impl=impl,
        **kw,
    )


def _exchange(conf, M=3 * N_EXEC, R=8):
    cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
    meta, oracle = _write_skewed(cluster, 0, M, R)
    cluster.run_exchange(0)
    return cluster, meta, oracle


class TestClusterBitEquality:
    def _assert_matches(self, base_meta, meta):
        assert len(meta.recv_sizes) == len(base_meta.recv_sizes)
        for rnd in range(len(base_meta.recv_sizes)):
            np.testing.assert_array_equal(
                meta.recv_sizes[rnd], base_meta.recv_sizes[rnd]
            )
            for j in range(N_EXEC):
                used = int(base_meta.recv_sizes[rnd][j].sum()) * 128
                assert bytes(meta.recv_shards[rnd][j][:used]) == bytes(
                    base_meta.recv_shards[rnd][j][:used]
                )

    @pytest.mark.parametrize("mode", ["array", "memmap"])
    def test_pallas_matches_stock(self, mode, tmp_path):
        kw = {"spill_dir": str(tmp_path)} if mode == "memmap" else {}
        _, base_meta, oracle = _exchange(_conf("stock", mode=mode, **kw))
        cluster, meta, _ = _exchange(_conf("pallas", mode=mode, **kw))
        assert len(base_meta.recv_sizes) > 1, "should spill multiple rounds"
        self._assert_matches(base_meta, meta)
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    def test_device_mode(self):
        conf = _conf("pallas", mode="device", keep_device_recv=True)
        cluster, meta, oracle = _exchange(conf)
        assert meta.recv_shards is None and meta.recv_device is not None
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    @pytest.mark.parametrize("impl", ["pallas", "auto"])
    def test_quota_composition(self, impl):
        """The scheduled exchange under the skew planner's sub-round chunking:
        every sub-round routes through the scheduled kernel and the spliced
        receive state still matches the stock single-shot default."""
        _, base_meta, oracle = _exchange(_conf("stock"))
        cluster, meta, _ = _exchange(_conf(impl, quota=8))
        self._assert_matches(base_meta, meta)
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    def test_auto_resolves_stock_on_cpu(self):
        """auto on a CPU mesh must take the stock path (cache key proves the
        resolution; ISSUE 6 acceptance: stock stays the byte-for-byte
        default off-TPU)."""
        cluster, meta, oracle = _exchange(_conf("auto"))
        keys = [k for k in cluster._exchange_cache if k[0] != "gather"]
        assert keys and all(k[-1] == "stock" for k in keys)
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)

    def test_pallas_cache_key_is_pallas(self):
        cluster, meta, oracle = _exchange(_conf("pallas"))
        keys = [k for k in cluster._exchange_cache if k[0] != "gather"]
        assert keys and all(k[-1] == "pallas" for k in keys)
        _fetch_all(cluster, meta, 0, 3 * N_EXEC, 8, oracle)


# ----------------------------------------------------------------------
# true multi-controller lockstep (the test_spmd.py harness, pallas impl)


def test_two_process_spmd_exchange_pallas():
    """Both processes resolve exchange.impl=pallas and must build the SAME
    schedule: the scheduled permutes are collectives, so any asymmetry
    deadlocks or corrupts — CHILD's oracle check catches both."""
    from test_spmd import CHILD, ROOT, _free_port
    from sparkucx_tpu.parallel.bootstrap import DriverEndpoint

    driver = DriverEndpoint()
    coord = f"127.0.0.1:{_free_port()}"
    driver_addr = f"{driver.address[0]}:{driver.address[1]}"
    env = {k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["TEST_EXCHANGE_IMPL"] = "pallas"
    script = CHILD.format(root=ROOT)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(pid), coord, driver_addr],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=ROOT, env=env,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"child {pid} failed:\n{out[-3000:]}"
            assert f"CHILD_PASS pid={pid}" in out, out[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        driver.close()
