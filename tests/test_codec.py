"""Tests for the typed non-executing record codec (utils/codec.py) — the
data-plane default that replaces pickle on socket-delivered block payloads."""

import pickle

import numpy as np
import pytest

from sparkucx_tpu.utils.codec import (
    MAX_DEPTH,
    decode_records,
    encode_record,
    encode_records,
)


class TestRoundtrip:
    def test_scalar_shapes(self):
        vals = [
            None, True, False, 0, -1, 2**62, -(2**62), 2**100, -(2**100),
            0.0, -1.5, 3.141592653589793, float("inf"), "", "héllo ∆",
            b"", b"\x00\xff" * 100,
        ]
        for v in vals:
            got = list(decode_records(encode_record(v)))
            assert got == [v] and type(got[0]) is type(v), v

    def test_nan_roundtrip(self):
        (got,) = decode_records(encode_record(float("nan")))
        assert got != got  # NaN

    def test_containers(self):
        vals = [
            (), (1, "a", b"b"), [1, [2, [3]]], {"k": 1, 2: (3, 4)},
            ("key", {"nested": [1.5, None, True]}),
        ]
        for v in vals:
            (got,) = decode_records(encode_record(v))
            assert got == v and type(got) is type(v)

    def test_record_stream_concatenates(self):
        records = [(i, f"v{i}") for i in range(100)] + [None, (0, 0)]
        assert list(decode_records(encode_records(records))) == records

    def test_fuzz_random_kv_records(self, rng):
        for _ in range(20):
            records = [
                (int(rng.integers(-1e9, 1e9)), float(rng.normal()),
                 bytes(rng.integers(0, 256, size=int(rng.integers(0, 50)), dtype=np.uint8)))
                for _ in range(int(rng.integers(0, 40)))
            ]
            assert list(decode_records(encode_records(records))) == records

    def test_numpy_scalars_coerce(self):
        (got,) = decode_records(encode_record((np.int32(7), np.float32(0.5), np.bool_(True))))
        assert got == (7, 0.5, True)
        assert type(got[0]) is int and type(got[1]) is float and type(got[2]) is bool

    def test_empty_payload_yields_nothing(self):
        assert list(decode_records(b"")) == []


class TestZeroCopyByteLikes:
    """The zero-copy ``_encode`` branches (PERF.md codec microbench): bytes,
    bytearray, and memoryview append straight into the output buffer without
    an intermediate ``bytes()`` materialization.  All decode back as bytes."""

    def test_bytearray_roundtrip(self):
        src = bytearray(b"\x00\xff" * 500)
        (got,) = decode_records(encode_record(("k", src)))
        assert got == ("k", bytes(src)) and type(got[1]) is bytes

    def test_memoryview_flat_roundtrip(self):
        src = np.arange(256, dtype=np.uint8).tobytes()
        (got,) = decode_records(encode_record(memoryview(src)))
        assert got == src

    def test_memoryview_shaped_counts_bytes_not_elements(self):
        # len() on a shaped view counts ELEMENTS; the encoder must frame by
        # nbytes or the payload is silently truncated to the first dimension
        arr = np.arange(64, dtype=np.uint32).reshape(8, 8)
        mv = memoryview(arr)
        assert len(mv) != mv.nbytes  # the trap this test pins
        (got,) = decode_records(encode_record(mv))
        assert got == arr.tobytes()

    def test_memoryview_noncontiguous_copies_once_correctly(self):
        arr = np.arange(100, dtype=np.uint8)
        mv = memoryview(arr)[::2]  # strided: NOT contiguous
        assert not mv.contiguous
        (got,) = decode_records(encode_record(mv))
        assert got == arr[::2].tobytes()

    def test_bytes_mutation_after_encode_is_isolated(self):
        # the zero-copy append must COPY out of the source buffer (iadd
        # semantics), not alias it — later mutation can't corrupt the frame
        src = bytearray(b"before-mutation!")
        frame = encode_record(src)
        src[:] = b"AFTER-MUTATION!!"
        (got,) = decode_records(frame)
        assert got == b"before-mutation!"


class TestRejection:
    def test_unknown_tag(self):
        with pytest.raises(ValueError, match="unknown record tag"):
            list(decode_records(b"Z"))

    def test_truncated_scalar_and_length(self):
        for bad in (b"i\x00\x00", b"s\x00\x00\x00\x05ab", b"f", b"t\x00\x00"):
            with pytest.raises(ValueError, match="truncated"):
                list(decode_records(bad))

    def test_truncated_container_items(self):
        # tuple claims 3 items, carries 1
        with pytest.raises(ValueError, match="truncated"):
            list(decode_records(b"t\x00\x00\x00\x03N"))

    def test_over_deep_nesting_bounded(self):
        payload = b"t\x00\x00\x00\x01" * (MAX_DEPTH + 10) + b"N"
        with pytest.raises(ValueError, match="MAX_DEPTH"):
            list(decode_records(payload))

    def test_unencodable_type_raises(self):
        with pytest.raises(TypeError, match="safe codec"):
            encode_record(object())

    def test_unhashable_map_key_is_valueerror(self):
        # crafted frame: map of 1 entry whose key is an (empty) list — the
        # error contract promises ValueError, never a leaked TypeError
        with pytest.raises(ValueError, match="unhashable"):
            list(decode_records(b"m\x00\x00\x00\x01l\x00\x00\x00\x00N"))

    def test_pickle_payload_never_executes(self, tmp_path):
        """The canonical attack: a pickle whose deserialization has a side
        effect.  The default codec must raise, not execute."""
        canary = tmp_path / "owned"

        class Evil:
            def __reduce__(self):
                return (open, (str(canary), "w"))

        payload = pickle.dumps(Evil())
        with pytest.raises(ValueError):
            list(decode_records(payload))
        assert not canary.exists(), "decoding socket bytes executed code"


class TestReaderWiring:
    def test_default_deserializer_is_the_safe_codec(self, tmp_path):
        from sparkucx_tpu.shuffle.reader import default_deserializer, serialize_records

        records = [("k1", 1), ("k2", [2, 3])]
        assert list(default_deserializer(serialize_records(records))) == records
        # and it rejects pickle bytes rather than loading them
        canary = tmp_path / "owned"

        class Evil:
            def __reduce__(self):
                return (open, (str(canary), "w"))

        with pytest.raises(ValueError):
            list(default_deserializer(pickle.dumps(Evil())))
        assert not canary.exists()

    def test_pickle_optin_still_available(self):
        from sparkucx_tpu.shuffle.reader import (
            pickle_deserializer,
            pickle_serialize_records,
        )

        # sets are outside the safe codec's value set — the opt-in pickle
        # path is for exactly these arbitrary-object needs on trusted hosts
        recs = [{1, 2, 3}, frozenset({"a"})]
        assert list(pickle_deserializer(pickle_serialize_records(recs))) == recs
