"""Tests for the Pallas LSD radix sort (ops/radix.py) — differential fuzz
against numpy's stable sort in interpreter mode, Mosaic-lowering pin for the
TPU target, and the SortSpec.impl='radix' integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkucx_tpu.ops.radix import (
    BITS,
    NUM_BUCKETS,
    build_radix_sort,
    radix_sort_rows,
)


def _rows(keys: np.ndarray, width: int = 1, rng=None) -> np.ndarray:
    keys = np.asarray(keys, np.uint32)
    if rng is None:
        pay = np.arange(len(keys), dtype=np.int32)[:, None] * np.ones(
            width, np.int32
        )
    else:
        pay = rng.integers(-1000, 1000, size=(len(keys), width)).astype(np.int32)
    return np.concatenate([keys.view(np.int32)[:, None], pay], axis=1)


def _check(keys, tile_rows, width=1, rng=None):
    rows = _rows(keys, width, rng)
    out = np.asarray(
        radix_sort_rows(jnp.asarray(rows), tile_rows=tile_rows, interpret=True)
    )
    want = rows[np.argsort(np.asarray(keys, np.uint32), kind="stable")]
    np.testing.assert_array_equal(out, want)


class TestRadixCorrectness:
    def test_differential_fuzz(self, rng):
        """Random sizes, tile shapes, key ranges — including tiny keyspaces
        (mass duplication, the stability stressor) and full-range keys."""
        # each distinct (padded size, tile, width) compiles 8 interpreter
        # passes — keep the matrix small so the suite stays fast; the edge
        # tests below cover the degenerate patterns deterministically
        for tile, hi in ((64, 4), (128, 2**16), (256, 2**32)):
            n = int(rng.integers(10, 2000))
            keys = rng.integers(0, hi, size=n, dtype=np.uint64).astype(np.uint32)
            _check(keys, tile, width=int(rng.integers(1, 6)), rng=rng)

    def test_stability_heavy_duplicates(self, rng):
        # payload = row id: byte-exact equality proves stable order
        _check(rng.integers(0, 3, size=777), 128)

    def test_all_equal_and_extremes(self, rng):
        _check(np.full(300, 7, np.uint32), 64)
        _check(np.full(300, 0xFFFFFFFF, np.uint32), 64)
        _check(np.zeros(300, np.uint32), 64)

    def test_sign_bit_keys_unsigned_order(self, rng):
        """Keys above 2^31 bitcast to negative int32 lanes — the sort must
        still order them as uint32."""
        keys = np.array([0, 2**31, 2**31 - 1, 0xFFFFFFFF, 5], np.uint32)
        _check(keys, 64)

    def test_non_tile_multiple_padding(self, rng):
        keys = rng.integers(0, 2**32, size=1000, dtype=np.uint64).astype(np.uint32)
        _check(keys, 96)  # 1000 -> padded to 1056, pad rows sliced back off

    def test_single_row_and_tiny(self, rng):
        _check(np.array([42], np.uint32), 64)
        _check(np.array([3, 1], np.uint32), 64)

    def test_odd_row_count_tile_clamp(self, rng):
        """Oversized tile + odd n: the clamp must stay a sublane multiple
        (min(tile, n) at n=1001 gave a 1001-row tile the module's own
        SPARKUCX_RADIX_TILE validation rejects) — and still sort correctly."""
        from sparkucx_tpu.ops.radix import clamped_tile_rows

        assert clamped_tile_rows(2048, 1001) == 1008
        for tile, n in ((2048, 1001), (64, 3), (8, 9)):
            got = clamped_tile_rows(tile, n)
            assert got % 8 == 0 and got >= 8
        keys = rng.integers(0, 2**32, size=1001, dtype=np.uint64).astype(np.uint32)
        _check(keys, 2048)  # clamp engages: tile > n

    def test_float32_rows_pad_keys_bitcast(self, rng):
        """Float payload dtype + tile padding: pad keys must be BITCAST
        KEY_MAX (a value cast would make pad rows sort mid-array and push
        real high-key rows off the [:n] slice — review r5 finding)."""
        n = 12  # not a multiple of tile_rows=8 -> 4 pad rows
        keys = np.array(
            [0xD0327A78, 0xE9AA5979, 0xF0000000, 0xBF800001, 0, 1, 2, 3, 4, 5, 6, 7],
            np.uint32,
        )
        pay = rng.normal(size=(n, 2)).astype(np.float32)
        rows = np.concatenate([keys.view(np.float32)[:, None], pay], axis=1)
        out = np.asarray(
            radix_sort_rows(jnp.asarray(rows), tile_rows=8, interpret=True)
        )
        want = rows[np.argsort(keys, kind="stable")]
        np.testing.assert_array_equal(out.view(np.uint32), want.view(np.uint32))


def _mosaic_lowers_gather() -> bool:
    """Whether this JAX's Mosaic TPU lowering has a rule for lax.gather at all
    (absent before 0.5 — the kernel's dynamic_gather spelling cannot lower)."""
    try:
        from jax._src.pallas.mosaic import lowering as _ml

        return jax.lax.gather_p in _ml.lowering_rules
    except Exception:
        return True  # registry moved: assume capable and let the test decide


class TestRadixLowering:
    def test_tpu_aot_lowering(self):
        """Pin Mosaic compatibility without a chip: every primitive in the
        non-interpret kernel must lower for the TPU target (this is what
        caught jnp int-indexing -> dynamic_slice and take_along_axis's
        unsupported gather spelling)."""
        import pytest

        if not _mosaic_lowers_gather():
            pytest.skip("Mosaic has no lax.gather lowering rule on this JAX (< 0.5)")
        from jax import export as jax_export  # jax.export is lazily loaded pre-0.5

        fn = build_radix_sort(1 << 15, 25)
        x = jax.ShapeDtypeStruct((1 << 15, 25), jnp.int32)
        exported = jax_export.export(fn, platforms=["tpu"])(x)
        assert len(exported.mlir_module_serialized) > 0

    def test_pass_count_covers_key(self):
        assert BITS * (32 // BITS) == 32
        assert NUM_BUCKETS == 1 << BITS


class TestSortSpecRadix:
    def test_driver_radix_vs_oracle(self, rng):
        from sparkucx_tpu.ops.exchange import make_mesh
        from sparkucx_tpu.ops.sort import SortSpec, oracle_sort, run_distributed_sort

        mesh = make_mesh(1)
        n = 3000
        keys = rng.integers(0, 2**32, size=n, dtype=np.uint64).astype(np.uint32)
        pay = rng.integers(-99, 99, size=(n, 4)).astype(np.int32)
        spec = SortSpec(
            num_executors=1, capacity=4096, recv_capacity=4096, width=4, impl="radix"
        )
        sk, sp = run_distributed_sort(mesh, spec, keys, pay)
        wk, wp = oracle_sort(keys, pay)
        np.testing.assert_array_equal(sk, wk)
        np.testing.assert_array_equal(sp, wp)

    def test_radix_requires_single_executor(self):
        from sparkucx_tpu.ops.sort import SortSpec

        with pytest.raises(ValueError, match="radix"):
            SortSpec(
                num_executors=2, capacity=8, recv_capacity=16, impl="radix"
            ).validate()

    def test_valid_keymax_rows_sort_before_padding(self, rng):
        """Valid rows carrying the KEY_MAX sentinel must keep their payload
        and precede nothing (they are last) but stay ahead of zeroed padding
        in the stable order — the ops/sort.py padding discipline."""
        from sparkucx_tpu.ops.exchange import make_mesh
        from sparkucx_tpu.ops.sort import KEY_MAX, SortSpec, run_distributed_sort

        mesh = make_mesh(1)
        keys = np.array([5, KEY_MAX, 1, KEY_MAX], np.uint32)
        pay = np.array([[50], [91], [10], [92]], np.int32)
        spec = SortSpec(
            num_executors=1, capacity=8, recv_capacity=8, width=1, impl="radix"
        )
        sk, sp = run_distributed_sort(mesh, spec, keys, pay)
        assert sk.tolist() == [1, 5, int(KEY_MAX), int(KEY_MAX)]
        assert sp[:, 0].tolist() == [10, 50, 91, 92]  # stable among KEY_MAX
