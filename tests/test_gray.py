"""Gray-failure robustness: health scoring, breakers, hedges, load shedding.

Pins the PR's contracts:

* per-peer health scoring (latency/error EWMAs) and the closed -> open ->
  half-open circuit breaker (``breaker.failureThreshold`` /
  ``breaker.cooldownMs``),
* hedged fetches (``fetch.hedgeMs`` / ``fetch.hedgeMaxMs``): a straggling
  block gets a duplicate request to a replica holder, first completion wins
  bit-identically, the loser is quarantined,
* memory-pressure watermarks (``store.softWatermark`` /
  ``store.hardWatermark``): soft kicks an out-of-band eviction sweep, hard
  sheds allocation-bearing writes/serves with the typed RETRYABLE
  ``ResourceExhaustedError`` (size code -4 on the wire),
* reactor load shedding (``server.acceptBacklog``): over-backlog accepts get
  a best-effort ServerBusy frame and a typed client-side error,
* the acceptance chaos scenario: one primary STALLED (not killed) — hedged
  fetches complete bit-identically from replicas with zero deadline expiries.

Every knob defaults off/0 = the byte-identical wire and store (golden frames
pinned by tests/test_obs.py::TestGoldenFramesUnchanged).
"""

import os
import socket
import time

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock
from sparkucx_tpu.core.definitions import (
    AmId,
    FRAME_HEADER_SIZE,
    unpack_frame_header,
)
from sparkucx_tpu.core.operation import (
    OperationStatus,
    ResourceExhaustedError,
    TransportError,
)
from sparkucx_tpu.shuffle.reader import TpuShuffleReader
from sparkucx_tpu.shuffle.resolver import ring_neighbors
from sparkucx_tpu.testing import faults
from sparkucx_tpu.transport.peer import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    PeerTransport,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


def _cluster(n, **conf_kw):
    conf_kw.setdefault("staging_capacity_per_executor", 1 << 20)
    conf = TpuShuffleConf(**conf_kw)
    ts = [PeerTransport(conf, executor_id=i) for i in range(n)]
    addrs = [t.init() for t in ts]
    for t in ts:
        for j, a in enumerate(addrs):
            if j != t.executor_id:
                t.add_executor(j, a)
    return ts, addrs


def _close_all(ts):
    for t in ts:
        t.close()


def _chaos_seed(default):
    """Payload seed for the acceptance scenarios: CI's chaos matrix re-runs
    them with ``SPARKUCX_TPU_CHAOS_SEED={1,2,3}`` to prove gray-failure
    recovery is seed-independent, not a golden-path accident."""
    return int(os.environ.get("SPARKUCX_TPU_CHAOS_SEED", default))


def _stage(t, shuffle_id, num_mappers, num_reducers, seed=0):
    rng = np.random.default_rng(seed)
    t.store.create_shuffle(shuffle_id, num_mappers, num_reducers)
    payloads = {}
    for m in range(num_mappers):
        w = t.store.map_writer(shuffle_id, m)
        for r in range(num_reducers):
            data = rng.integers(0, 256, size=200 + 37 * (m + r), dtype=np.uint8).tobytes()
            payloads[(m, r)] = data
            w.write_partition(r, data)
        w.commit()
    return payloads


def _reader(transport, payloads, num_mappers, num_reducers, executors, **kw):
    kw.setdefault("fetch_retries", 2)
    kw.setdefault("fetch_deadline_ms", 2000)
    kw.setdefault("fetch_backoff_ms", 10)
    return TpuShuffleReader(
        transport,
        executor_id=transport.executor_id,
        shuffle_id=0,
        start_partition=0,
        end_partition=num_reducers,
        num_mappers=num_mappers,
        block_sizes=lambda m, r: len(payloads[(m, r)]),
        max_blocks_per_request=1,
        sender_of=lambda m: 1,
        replica_of=lambda primary: ring_neighbors(primary, executors, 1),
        **kw,
    )


# ---------------------------------------------------------------------------
# knob parsing + byte-identical defaults
# ---------------------------------------------------------------------------


class TestGrayKnobs:
    def test_knob_parsing_from_spark_conf(self):
        conf = TpuShuffleConf.from_spark_conf(
            {
                "spark.shuffle.tpu.fetch.hedgeMs": "40",
                "spark.shuffle.tpu.fetch.hedgeMaxMs": "250",
                "spark.shuffle.tpu.breaker.failureThreshold": "3",
                "spark.shuffle.tpu.breaker.cooldownMs": "500",
                "spark.shuffle.tpu.store.softWatermark": "64m",
                "spark.shuffle.tpu.store.hardWatermark": "128m",
                "spark.shuffle.tpu.server.acceptBacklog": "2048",
            }
        )
        assert conf.fetch_hedge_ms == 40
        assert conf.fetch_hedge_max_ms == 250
        assert conf.breaker_failure_threshold == 3
        assert conf.breaker_cooldown_ms == 500
        assert conf.store_soft_watermark == 64 * 1024 * 1024
        assert conf.store_hard_watermark == 128 * 1024 * 1024
        assert conf.server_accept_backlog == 2048

    def test_defaults_are_off(self):
        """Every gray-failure knob defaults to 0/off: no hedges, no breaker
        trips, no watermarks, no shedding — the byte-identical plane."""
        conf = TpuShuffleConf()
        assert conf.fetch_hedge_ms == 0
        assert conf.fetch_hedge_max_ms == 0
        assert conf.breaker_failure_threshold == 0
        assert conf.breaker_cooldown_ms == 1000  # latent until threshold > 0
        assert conf.store_soft_watermark == 0
        assert conf.store_hard_watermark == 0
        assert conf.server_accept_backlog == 0


# ---------------------------------------------------------------------------
# peer health scoring + circuit breakers
# ---------------------------------------------------------------------------


class TestBreaker:
    def _transport(self, **conf_kw):
        conf_kw.setdefault("staging_capacity_per_executor", 1 << 20)
        return PeerTransport(TpuShuffleConf(**conf_kw), executor_id=0)

    def test_scoring_without_threshold_never_trips(self):
        t = self._transport()
        try:
            for _ in range(50):
                t.record_peer_failure(7, "synthetic")
            assert t.breaker_state(7) == BREAKER_CLOSED
            assert t.breaker_allows(7)
            snap = t.health_snapshot()[7]
            assert snap["failures"] == 50 and snap["trips"] == 0
            assert snap["error_ewma"] > 0.9  # EWMA converged toward 1.0
        finally:
            t.close()

    def test_trip_cooldown_half_open_probe_close(self):
        t = self._transport(breaker_failure_threshold=3, breaker_cooldown_ms=50)
        try:
            t.record_peer_failure(7)
            t.record_peer_failure(7)
            assert t.breaker_state(7) == BREAKER_CLOSED  # streak below threshold
            t.record_peer_failure(7)
            assert t.breaker_state(7) == BREAKER_OPEN
            assert not t.breaker_allows(7)  # open rejects inside cooldown
            assert t.health_snapshot()[7]["trips"] == 1
            time.sleep(0.06)
            assert t.breaker_allows(7)  # cooldown elapsed: ONE probe admitted
            assert t.breaker_state(7) == BREAKER_HALF_OPEN
            assert not t.breaker_allows(7)  # second probe rejected in flight
            t.record_peer_success(7, latency_ns=1_000_000)
            assert t.breaker_state(7) == BREAKER_CLOSED
            assert t.breaker_allows(7)
            snap = t.health_snapshot()[7]
            assert snap["consecutive_failures"] == 0
            assert snap["latency_ewma_ns"] == 1_000_000
        finally:
            t.close()

    def test_failed_probe_reopens_with_fresh_cooldown(self):
        t = self._transport(breaker_failure_threshold=2, breaker_cooldown_ms=40)
        try:
            t.record_peer_failure(3)
            t.record_peer_failure(3)
            time.sleep(0.05)
            assert t.breaker_allows(3)  # half-open probe
            t.record_peer_failure(3)  # probe failed
            assert t.breaker_state(3) == BREAKER_OPEN
            assert not t.breaker_allows(3)  # cooldown restarted
            assert t.health_snapshot()[3]["trips"] == 2
        finally:
            t.close()

    def test_success_resets_streak(self):
        t = self._transport(breaker_failure_threshold=3)
        try:
            t.record_peer_failure(5)
            t.record_peer_failure(5)
            t.record_peer_success(5)
            t.record_peer_failure(5)
            t.record_peer_failure(5)
            assert t.breaker_state(5) == BREAKER_CLOSED  # streak broken at 2
        finally:
            t.close()

    def test_health_view_rollup(self):
        t = self._transport(breaker_failure_threshold=1, breaker_cooldown_ms=60_000)
        try:
            assert t._health_view() == {}  # nothing scored yet: empty family
            t.record_peer_success(1, latency_ns=2_000_000)
            t.record_peer_failure(2)
            view = t._health_view()
            assert view["peers"] == 2
            assert view["open"] == 1 and view["half_open"] == 0
            assert view["successes"] == 1 and view["failures"] == 1
            assert view["trips"] == 1
            assert view["latency_ewma_ns_max"] == 2_000_000
            # the roll-up rides the metrics registry as the `health` family
            text = t.metrics.prometheus_text()
            assert "sparkucx_tpu_health_open" in text
        finally:
            t.close()

    def test_wire_failure_feeds_breaker_and_routes_to_replica(self):
        """A dead primary trips the breaker via the wire-observation path, and
        the reader's candidate filter skips the open breaker — the replica
        serves without burning the primary's full deadline again."""
        ts, _ = _cluster(
            3,
            replication_factor=1,
            wire_timeout_ms=5000,
            breaker_failure_threshold=1,
            breaker_cooldown_ms=60_000,
        )
        try:
            payloads = _stage(ts[1], 0, 2, 3, seed=11)
            ts[1].store.seal(0)
            assert ts[1].replication_wait(0, timeout=10.0)
            faults.kill_executor(ts[1])
            reader = _reader(ts[0], payloads, 2, 3, executors=[0, 1, 2])
            got = {}
            for blk in reader.fetch_blocks():
                got[(blk.block_id.map_id, blk.block_id.reduce_id)] = bytes(blk.data)
                blk.release()
            assert got == payloads  # bit-identical through the failover
            assert ts[0].breaker_state(1) == BREAKER_OPEN
            assert ts[0].health_snapshot()[1]["failures"] >= 1
            assert reader.metrics.failovers >= 1
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# memory-pressure watermarks + load shedding (store / pool / wire)
# ---------------------------------------------------------------------------


class TestMemoryPressure:
    def test_hard_watermark_sheds_staging_write_typed(self):
        ts, _ = _cluster(1, store_hard_watermark=512)
        try:
            ts[0].store.create_shuffle(1, 1, 1)
            w = ts[0].store.map_writer(1, 0)
            with pytest.raises(ResourceExhaustedError) as ei:
                w.write_partition(0, b"x" * 600)
            e = ei.value
            assert isinstance(e, TransportError)  # old catch-sites still work
            assert e.requested >= 600
            assert e.watermark == 512
            assert "store hard watermark" in str(e)
            # the shed write left the store exactly as it was
            assert ts[0].store.memory_pressure_bytes() == 0
        finally:
            _close_all(ts)

    def test_soft_watermark_kicks_single_flight_sweep(self):
        from sparkucx_tpu.service.eviction import EvictionManager

        ts, _ = _cluster(1, store_soft_watermark=256)
        try:
            ts[0].store.eviction = EvictionManager(ts[0].store)
            _stage(ts[0], 2, 1, 2, seed=3)  # crosses 256 B of staged bytes
            stats = ts[0].store.watermark_stats()
            assert stats["watermark_sweeps"] >= 1
            assert stats["pressure_bytes"] > 256
        finally:
            _close_all(ts)

    def test_soft_watermark_without_eviction_manager_is_inert(self):
        ts, _ = _cluster(1, store_soft_watermark=256)
        try:
            payloads = _stage(ts[0], 2, 1, 2, seed=3)  # no manager: no sweep
            assert ts[0].store.watermark_stats()["watermark_sweeps"] == 0
            for (m, r), data in payloads.items():
                assert ts[0].store.read_block(2, m, r) == data
        finally:
            _close_all(ts)

    def test_pool_budget_sheds_slab_growth_typed(self):
        from sparkucx_tpu.memory.pool import MemoryPool

        pool = MemoryPool(TpuShuffleConf(store_hard_watermark=1))
        try:
            with pytest.raises(ResourceExhaustedError, match="memory pool hard watermark"):
                pool.get(64)
        finally:
            pool.close()

    def test_pool_budget_zero_is_unbounded(self):
        from sparkucx_tpu.memory.pool import MemoryPool

        pool = MemoryPool(TpuShuffleConf())
        try:
            mb = pool.get(64)
            assert mb.size == 64
            mb.close()
        finally:
            pool.close()

    def test_replica_put_shed_discards_without_ack(self):
        """A pressured replica holder drops the REPLICA_PUT (no ack) instead
        of dying: the pusher's replication_wait reports unsettled, exactly
        like the sever case, and both executors stay serviceable."""
        ts, _ = _cluster(2, replication_factor=1)
        try:
            faults.arm(
                "store.mem_pressure",
                faults.fail(ResourceExhaustedError(detail="injected pressure")),
                match={"site": "put_replica"},
            )
            payloads = _stage(ts[0], 5, 1, 1)
            ts[0].store.seal(5)
            assert not ts[0].replication_wait(5, timeout=0.7)
            assert ts[1].store.replica_view(5, 0, 0) is None
            # the holder itself is fine — primary reads still serve
            assert ts[0].store.read_block(5, 0, 0) == payloads[(0, 0)]
        finally:
            _close_all(ts)

    def test_shed_restage_retries_and_recovers(self):
        """Acceptance: under an injected hard-watermark shed the client gets
        the typed RETRYABLE error over the wire (size code -4), backs off,
        retries, and completes bit-identically — no OOM, no hang."""
        from sparkucx_tpu.service.eviction import EvictionManager

        ts, _ = _cluster(3, replication_factor=1, wire_timeout_ms=5000)
        try:
            payloads = _stage(ts[1], 0, 2, 3, seed=_chaos_seed(9))
            ts[1].store.seal(0)
            assert ts[1].replication_wait(0, timeout=10.0)
            ts[1].store.eviction = EvictionManager(ts[1].store)
            while ts[1].store.round_tier(0, 0) != "disk":
                assert ts[1].store.demote_round(0, 0) is not None
            # first restage attempt hits (injected) memory pressure: the
            # serve fails typed-retryable; the reader's backoff retry lands
            # after the pressure "cleared" (times=1) and restages fine
            faults.arm(
                "store.mem_pressure",
                faults.fail(ResourceExhaustedError(detail="injected pressure")),
                times=1,
                match={"site": "restage_round"},
            )
            reader = _reader(ts[0], payloads, 2, 3, executors=[0, 1, 2])
            got = {}
            for blk in reader.fetch_blocks():
                got[(blk.block_id.map_id, blk.block_id.reduce_id)] = bytes(blk.data)
                blk.release()
            assert got == payloads  # bit-identical through the shed
            assert faults.fired["store.mem_pressure"] == 1
            assert reader.metrics.blocks_retried + reader.metrics.failovers >= 1
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# reactor load shedding (server.acceptBacklog -> ServerBusy)
# ---------------------------------------------------------------------------


class TestAcceptShedding:
    def test_reactor_sheds_over_backlog_with_busy_frame(self):
        from sparkucx_tpu.service.reactor import Reactor

        r = Reactor(workers=1, name="test-shed", accept_backlog=1)
        srv = socket.socket()
        try:
            srv.bind(("127.0.0.1", 0))
            srv.listen(16)
            addr = srv.getsockname()

            def serve_once(conn):
                return bool(conn.recv(64))

            r.add_listener(srv, lambda c: r.add_connection(c, serve_once))
            first = socket.create_connection(addr, timeout=5)
            deadline = time.monotonic() + 5
            while r.num_connections < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert r.num_connections == 1  # resident, inside the backlog
            second = socket.create_connection(addr, timeout=5)
            second.settimeout(5)
            hdr = b""
            while len(hdr) < FRAME_HEADER_SIZE:
                chunk = second.recv(FRAME_HEADER_SIZE - len(hdr))
                if not chunk:
                    break
                hdr += chunk
            am_id, hlen, blen = unpack_frame_header(hdr)
            assert am_id == AmId.SERVER_BUSY  # typed busy reply...
            assert hlen == 0 and blen == 0  # ...headerless and bodyless
            assert second.recv(1) == b""  # ...then an immediate close
            assert r.stats()["sheds"] == 1
            assert r.num_connections == 1  # the resident conn was untouched
            first.close()
            second.close()
        finally:
            r.close()
            srv.close()

    def test_shed_fetch_fails_typed_retryable(self):
        """End to end over the peer plane: a raw connection parks inside the
        backlog, the transport's fetch connection is shed, and the in-flight
        request dies with the RETRYABLE ResourceExhaustedError — not the
        generic connection-lost TransportError."""
        ts, addrs = _cluster(2, server_accept_backlog=1)
        try:
            host, _, port = addrs[1].decode().rpartition(":")
            parked = socket.create_connection((host, int(port)), timeout=5)
            reactor = ts[1].server._reactor
            deadline = time.monotonic() + 5
            while reactor.num_connections < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert reactor.num_connections == 1
            buf = _buf(64)
            req = ts[0].fetch_block(1, 0, 0, 0, buf)
            deadline = time.monotonic() + 5
            while not req.completed() and time.monotonic() < deadline:
                ts[0].progress()
                time.sleep(0.002)
            assert req.completed()
            res = req.wait(1)
            assert res.status == OperationStatus.FAILURE
            assert isinstance(res.error, ResourceExhaustedError)
            assert "accept backlog" in str(res.error)
            parked.close()
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# hedged fetches
# ---------------------------------------------------------------------------


class TestHedgedFetches:
    def test_hedge_delay_clamped_between_floor_and_ceiling(self):
        ts, _ = _cluster(1)
        try:
            payloads = {(0, 0): b"x" * 64}
            r = _reader(
                ts[0], payloads, 1, 1, executors=[0],
                fetch_hedge_ms=40, fetch_hedge_max_ms=100,
            )
            delay = r._hedge_delay_ns()
            assert 40 * 1_000_000 <= delay <= 100 * 1_000_000
            off = _reader(ts[0], payloads, 1, 1, executors=[0])
            assert off._hedge_delay_ns() == 0  # default: hedging off
        finally:
            _close_all(ts)

    def test_stalled_primary_hedge_wins_bit_identical(self):
        """The acceptance chaos scenario: the primary is STALLED (gray), not
        killed — every frame it serves sleeps well past the hedge delay.
        Hedged fetches complete from the replica ring bit-identically, with
        zero deadline expiries and the stall never dominating the read."""
        ts, _ = _cluster(3, replication_factor=1, wire_timeout_ms=10_000)
        try:
            payloads = _stage(ts[1], 0, 2, 3, seed=_chaos_seed(42))
            ts[1].store.seal(0)
            assert ts[1].replication_wait(0, timeout=10.0)
            # stall ONLY the primary's serving plane (executor 1); the faults
            # registry is process-global, so the match key pins one server
            faults.arm("peer.server.frame", faults.stall(0.25), match={"executor": 1})
            reader = _reader(
                ts[0], payloads, 2, 3, executors=[0, 1, 2],
                fetch_deadline_ms=5000,
                fetch_hedge_ms=40, fetch_hedge_max_ms=60,
            )
            t0 = time.monotonic()
            got = {}
            for blk in reader.fetch_blocks():
                got[(blk.block_id.map_id, blk.block_id.reduce_id)] = bytes(blk.data)
                blk.release()
            elapsed = time.monotonic() - t0
            assert got == payloads  # bit-identical from the replica holders
            m = reader.metrics
            assert m.hedges_issued >= 1
            assert m.hedge_wins >= 1
            assert m.fetch_timeouts == 0  # zero deadline expiries
            # 6 windows x 0.25 s of stall would be >= 1.5 s un-hedged; hedges
            # must keep the read well under the sum of the stalls
            assert elapsed < 1.5
        finally:
            _close_all(ts)

    def test_healthy_cluster_hedges_lose_quietly(self):
        """With hedging on but nobody straggling slower than the hedge delay,
        any hedge that does fire loses to the primary and is quarantined —
        the output is untouched and nothing leaks."""
        ts, _ = _cluster(3, replication_factor=1)
        try:
            payloads = _stage(ts[1], 0, 2, 3, seed=8)
            ts[1].store.seal(0)
            assert ts[1].replication_wait(0, timeout=10.0)
            reader = _reader(
                ts[0], payloads, 2, 3, executors=[0, 1, 2],
                fetch_hedge_ms=2000, fetch_hedge_max_ms=2000,
            )
            got = {}
            for blk in reader.fetch_blocks():
                got[(blk.block_id.map_id, blk.block_id.reduce_id)] = bytes(blk.data)
                blk.release()
            assert got == payloads
            assert reader.metrics.hedge_wins == 0  # primary always beat 2 s
        finally:
            _close_all(ts)

    def test_hedging_off_by_default(self):
        ts, _ = _cluster(3, replication_factor=1)
        try:
            payloads = _stage(ts[1], 0, 1, 2, seed=4)
            ts[1].store.seal(0)
            assert ts[1].replication_wait(0, timeout=10.0)
            reader = _reader(ts[0], payloads, 1, 2, executors=[0, 1, 2])
            got = {}
            for blk in reader.fetch_blocks():
                got[(blk.block_id.map_id, blk.block_id.reduce_id)] = bytes(blk.data)
                blk.release()
            assert got == payloads
            assert reader.metrics.hedges_issued == 0
        finally:
            _close_all(ts)
