"""Popularity-aware serving tier: hot-block fanout + serve-side read cache.

Pins the PR's contracts:

* per-block fetch-rate EWMAs (``serve.hotThresholdFetchesPerSec``): promote
  on a fetch storm, demote on cooling with hysteresis (demote edge = half the
  promote edge), idle-entry GC — all on an injectable clock,
* the bounded serve-side decoded-block cache (``serve.cacheBytes``):
  byte-budgeted LRU above the eviction tiers, charged against the owning
  tenant's quota, evictions release their charges,
* hot promotion widens the replica set beyond ``replication.factor`` ring
  successors (``serve.hotReplicas``) over the existing REPLICA_PUT plane and
  advertises the holder set over HOT_SET_PULL; cool-down drops only the
  advertisement (replicas never fall below the fault-tolerance floor),
* reader-side load spreading: deterministic-per-reader rotation over the
  advertised holders, hedges prefer a holder DIFFERENT from the executor the
  straggling fetch actually targeted,
* the encoded-chunk pool is LRU under ``compress.cacheBytes`` with
  hit/miss/eviction counters,
* the chaos lane: one hot-block holder killed mid-storm, reads stay
  bit-identical,
* every knob defaults off = byte-identical wire + store (the golden frames
  of tests/test_obs.py::TestGoldenFramesUnchanged stay pinned).
"""

import time

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.definitions import AmId, pack_hot_set, unpack_hot_set
from sparkucx_tpu.core.operation import OperationStatus, TransportError
from sparkucx_tpu.service.eviction import ServeCache
from sparkucx_tpu.service.tenants import TenantRegistry
from sparkucx_tpu.shuffle.reader import TpuShuffleReader
from sparkucx_tpu.shuffle.resolver import ring_neighbors, widened_ring_neighbors
from sparkucx_tpu.store.hbm_store import BlockPopularity, HbmBlockStore
from sparkucx_tpu.testing import faults
from sparkucx_tpu.transport.peer import PeerTransport


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


def _cluster(n, **conf_kw):
    conf_kw.setdefault("staging_capacity_per_executor", 1 << 20)
    conf = TpuShuffleConf(**conf_kw)
    ts = [PeerTransport(conf, executor_id=i) for i in range(n)]
    addrs = [t.init() for t in ts]
    for t in ts:
        for j, a in enumerate(addrs):
            if j != t.executor_id:
                t.add_executor(j, a)
    return ts


def _close_all(ts):
    for t in ts:
        t.close()


def _stage(t, shuffle_id, num_mappers, num_reducers, seed=0):
    rng = np.random.default_rng(seed)
    t.store.create_shuffle(shuffle_id, num_mappers, num_reducers)
    payloads = {}
    for m in range(num_mappers):
        w = t.store.map_writer(shuffle_id, m)
        for r in range(num_reducers):
            data = rng.integers(0, 256, size=200 + 37 * (m + r), dtype=np.uint8).tobytes()
            payloads[(m, r)] = data
            w.write_partition(r, data)
        w.commit()
    return payloads


def _fetch_one(t, peer, sid, m, r, size, timeout=5.0):
    buf = _buf(size)
    req = t.fetch_block(peer, sid, m, r, buf)
    deadline = time.monotonic() + timeout
    while not req.completed() and time.monotonic() < deadline:
        t.progress()
    res = req.wait(1)
    assert res.status == OperationStatus.SUCCESS, str(res.error)
    return buf.host_view()[:size].tobytes()


def _storm(t, peer, sid, m, r, size, rounds=6):
    """Hot loop on one block: back-to-back fetches push its rate EWMA far
    past any CI-realistic threshold."""
    out = None
    for _ in range(rounds):
        out = _fetch_one(t, peer, sid, m, r, size)
    return out


# ---------------------------------------------------------------------------
# knobs: parsing + defaults-off
# ---------------------------------------------------------------------------


class TestServeKnobs:
    def test_knob_parsing_from_spark_conf(self):
        conf = TpuShuffleConf.from_spark_conf(
            {
                "spark.shuffle.tpu.serve.hotThresholdFetchesPerSec": "25",
                "spark.shuffle.tpu.serve.hotReplicas": "3",
                "spark.shuffle.tpu.serve.cacheBytes": "4m",
                "spark.shuffle.tpu.serve.holdersTtlMs": "100",
                "spark.shuffle.tpu.compress.cacheBytes": "2m",
            }
        )
        assert conf.serve_hot_threshold_fetches_per_sec == 25.0
        assert conf.serve_hot_replicas == 3
        assert conf.serve_cache_bytes == 4 << 20
        assert conf.serve_holders_ttl_ms == 100
        assert conf.compress_cache_bytes == 2 << 20

    def test_defaults_are_off(self):
        """Threshold 0 = no tracker, no HOT_SET_PULL traffic, no serve cache;
        the compress pool cap keeps its historical 128 MiB default, the
        holder-set TTL its historical 250 ms."""
        conf = TpuShuffleConf()
        assert conf.serve_hot_threshold_fetches_per_sec == 0.0
        assert conf.serve_cache_bytes == 0
        assert conf.compress_cache_bytes == 128 << 20
        assert conf.serve_hot_replicas == 4  # inert while the threshold is 0
        assert conf.serve_holders_ttl_ms == 250  # inert while the threshold is 0

    def test_validation_rejects_negative(self):
        with pytest.raises(ValueError):
            TpuShuffleConf(serve_hot_threshold_fetches_per_sec=-1).validate()
        with pytest.raises(ValueError):
            TpuShuffleConf(serve_cache_bytes=-1).validate()
        with pytest.raises(ValueError):
            TpuShuffleConf(compress_cache_bytes=-1).validate()
        with pytest.raises(ValueError):
            TpuShuffleConf(serve_holders_ttl_ms=-1).validate()

    def test_holders_ttl_governs_pull_rate(self, monkeypatch):
        """The hot_holders cache honors ``serve.holdersTtlMs``: a long TTL
        serves the cached table without a HOT_SET_PULL round-trip; TTL 0
        means every call re-pulls (the freshest-possible setting)."""
        ts = _cluster(
            2, serve_hot_threshold_fetches_per_sec=5.0, serve_holders_ttl_ms=60_000
        )
        try:
            pulls = []
            real_pull = ts[1]._pull

            def counting_pull(eid, am_id, timeout=1.0):
                if am_id == AmId.HOT_SET_PULL:
                    pulls.append(eid)
                return real_pull(eid, am_id, timeout=timeout)

            monkeypatch.setattr(ts[1], "_pull", counting_pull)
            ts[1].hot_holders(0, 0)
            ts[1].hot_holders(0, 0)
            assert len(pulls) == 1  # second call inside the TTL: cached

            ts[1].conf.serve_holders_ttl_ms = 0
            ts[1].hot_holders(0, 0)
            ts[1].hot_holders(0, 0)
            assert len(pulls) == 3  # TTL 0: every call round-trips
        finally:
            _close_all(ts)

    def test_default_transport_has_no_popularity_plane(self):
        ts = _cluster(1)
        try:
            assert ts[0].popularity is None
            assert ts[0].store.serve_cache is None
            assert ts[0].hot_holders(0, 0) == []  # tier off: no pull, ever
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# BlockPopularity: EWMA promote/demote on an injected clock
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.ns = 0

    def __call__(self):
        return self.ns


class TestBlockPopularity:
    def test_storm_promotes_once_per_shuffle(self):
        clk = _Clock()
        pop = BlockPopularity(100.0, now_ns=clk)
        hot, trans = pop.observe(7, 0, 0)  # first sighting only records
        assert (hot, trans) == (False, [])
        clk.ns += 1_000_000  # 1 ms apart = 1000 fetches/sec instantaneous
        hot, trans = pop.observe(7, 0, 0)
        assert hot and trans == [(7, True)]  # ewma = 0.25 * 1000 >= 100
        clk.ns += 1_000_000
        hot, trans = pop.observe(7, 0, 1)  # second block heats up
        assert trans == []  # no first sighting yet
        clk.ns += 1_000_000
        hot, trans = pop.observe(7, 0, 1)
        assert hot and trans == []  # shuffle already hot: no new transition
        assert pop.is_hot(7) and pop.hot_shuffles() == [7]
        snap = pop.snapshot()
        assert snap["promotions"] == 2 and snap["hot_blocks"] == 2
        assert snap["hot_shuffles"] == 1

    def test_slow_fetches_never_promote(self):
        clk = _Clock()
        pop = BlockPopularity(100.0, now_ns=clk)
        for _ in range(50):
            clk.ns += 1_000_000_000  # 1/sec, threshold 100/sec
            hot, trans = pop.observe(3, 0, 0)
            assert not hot and trans == []
        assert not pop.is_hot(3)

    def test_cooling_demotes_with_hysteresis(self):
        clk = _Clock()
        pop = BlockPopularity(100.0, now_ns=clk)
        pop.observe(7, 0, 0)
        clk.ns += 1_000_000
        assert pop.observe(7, 0, 0)[0]  # hot at ewma 250
        # 5 ms of silence: effective rate min(250, 200) stays over the
        # demote edge (50) -> hysteresis holds the block hot
        assert pop.sweep(clk.ns + 5_000_000) == []
        assert pop.is_hot(7)
        # 100 ms of silence: effective rate 10 < 50 -> the shuffle's last
        # hot block cools and the demote transition fires
        assert pop.sweep(clk.ns + 100_000_000) == [(7, False)]
        assert not pop.is_hot(7)
        assert pop.snapshot()["demotions"] == 1

    def test_idle_cold_entries_are_forgotten(self):
        clk = _Clock()
        pop = BlockPopularity(100.0, now_ns=clk)
        pop.observe(1, 0, 0)
        assert pop.snapshot()["tracked_blocks"] == 1
        pop.sweep(clk.ns + 61 * 1_000_000_000)  # past _IDLE_GC_NS
        assert pop.snapshot()["tracked_blocks"] == 0

    def test_maybe_sweep_is_rate_limited(self):
        clk = _Clock()
        pop = BlockPopularity(100.0, now_ns=clk)
        pop.observe(7, 0, 0)
        clk.ns += 1_000_000
        pop.observe(7, 0, 0)
        clk.ns += 200_000_000_000  # everything long cold
        assert pop.maybe_sweep() == [(7, False)]  # first scan runs
        pop.observe(7, 1, 1)
        clk.ns += 500_000  # within the 1 s interval
        assert pop.maybe_sweep() == []  # rate-limited: no scan

    def test_threshold_zero_is_inert(self):
        pop = BlockPopularity(0.0, now_ns=_Clock())
        assert pop.observe(1, 0, 0) == (False, [])
        assert pop.maybe_sweep() == []
        assert pop.snapshot()["tracked_blocks"] == 0


# ---------------------------------------------------------------------------
# ServeCache: byte-budgeted LRU + tenant quota interplay
# ---------------------------------------------------------------------------


class TestServeCache:
    def test_lru_eviction_order_and_evicted_list(self):
        c = ServeCache(100)
        assert c.put((0, 0, 0), b"x" * 40) == []
        assert c.put((0, 0, 1), b"y" * 40) == []
        assert c.get((0, 0, 0)) == b"x" * 40  # refreshes (0,0,0) to MRU
        evicted = c.put((0, 0, 2), b"z" * 40)  # (0,0,1) is now LRU
        assert evicted == [((0, 0, 1), 40)]
        assert c.get((0, 0, 1)) is None
        assert c.get((0, 0, 0)) is not None
        assert c.used_bytes == 80 and len(c) == 2

    def test_oversized_block_rejected(self):
        c = ServeCache(10)
        assert c.put((0, 0, 0), b"a" * 11) == []
        assert len(c) == 0 and c.snapshot()["cache_rejects"] == 1

    def test_replace_refunds_previous_bytes(self):
        c = ServeCache(100)
        c.put((0, 0, 0), b"a" * 30)
        evicted = c.put((0, 0, 0), b"b" * 50)
        # the replaced payload's bytes come back so the caller releases them
        assert ((0, 0, 0), 30) in evicted
        assert c.used_bytes == 50 and c.get((0, 0, 0)) == b"b" * 50

    def test_invalidate_shuffle_drops_only_that_shuffle(self):
        c = ServeCache(1000)
        c.put((1, 0, 0), b"a" * 10)
        c.put((2, 0, 0), b"b" * 20)
        dropped = c.invalidate_shuffle(1)
        assert dropped == [((1, 0, 0), 10)]
        assert c.get((2, 0, 0)) is not None and c.used_bytes == 20

    def test_store_offer_charges_and_releases_tenant(self):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 20, serve_cache_bytes=600
        )
        store = HbmBlockStore(conf)
        try:
            reg = TenantRegistry(default_quota_bytes=1 << 20)
            reg.register("appA")
            store.tenants = reg
            store.create_shuffle(5, 1, 1, app_id="appA")
            base = reg.usage("appA")
            assert store.serve_cache_offer(5, 0, 0, b"p" * 500)
            assert reg.usage("appA") == base + 500
            # the next offer LRU-evicts the first entry: its charge comes back
            assert store.serve_cache_offer(5, 0, 1, b"q" * 400)
            assert reg.usage("appA") == base + 400
            arr, off, ln = store.serve_cache_get(5, 0, 1)
            assert bytes(arr[off : off + ln]) == b"q" * 400
        finally:
            store.close()

    def test_store_offer_respects_quota(self):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 20, serve_cache_bytes=1 << 20
        )
        store = HbmBlockStore(conf)
        try:
            reg = TenantRegistry(default_quota_bytes=100)
            reg.register("appB")
            store.tenants = reg
            store.create_shuffle(6, 1, 1, app_id="appB")
            used = reg.usage("appB")
            # no headroom for 200 bytes: the offer fails closed, no charge
            assert not store.serve_cache_offer(6, 0, 0, b"r" * 200)
            assert reg.usage("appB") == used
            assert store.serve_cache_get(6, 0, 0) is None
        finally:
            store.close()

    def test_remove_shuffle_invalidates_without_double_release(self):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 20, serve_cache_bytes=1 << 20
        )
        store = HbmBlockStore(conf)
        try:
            reg = TenantRegistry(default_quota_bytes=1 << 20)
            reg.register("appC")
            store.tenants = reg
            store.create_shuffle(7, 1, 1, app_id="appC")
            assert store.serve_cache_offer(7, 0, 0, b"s" * 300)
            store.remove_shuffle(7)
            # the blanket shuffle release already covered the cache charge;
            # a double release would drive usage negative
            assert reg.usage("appC") == 0
            assert store.serve_cache_get(7, 0, 0) is None
        finally:
            store.close()


# ---------------------------------------------------------------------------
# HOT_SET_PULL wire schema
# ---------------------------------------------------------------------------


class TestHotSetWire:
    def test_pack_unpack_roundtrip(self):
        table = {3: [0, 2, 5], 1: [4], 9: []}
        assert unpack_hot_set(pack_hot_set(table)) == {3: [0, 2, 5], 1: [4], 9: []}
        assert unpack_hot_set(pack_hot_set({})) == {}

    def test_pack_is_deterministic_sorted(self):
        a = pack_hot_set({2: [1, 0], 1: [3]})
        b = pack_hot_set({1: [3], 2: [0, 1]})
        assert a == b  # sorted shuffles, sorted holders: canonical bytes

    def test_am_id_pinned(self):
        assert AmId.HOT_SET_PULL == 14


# ---------------------------------------------------------------------------
# reader-side spreading + hedge-target choice
# ---------------------------------------------------------------------------


class _FakeReq:
    def completed(self):
        return False


class _FakeTransport:
    """Just enough surface for the hedge/spread unit paths."""

    executor_id = 0

    def __init__(self):
        self.hedged_to = []

    def fetch_block(self, executor_id, sid, m, r, buf):
        self.hedged_to.append(executor_id)
        return _FakeReq()


def _bare_reader(executor_id, holders_of=None, replica_of=None, **kw):
    payload_len = 64
    return TpuShuffleReader(
        _FakeTransport(),
        executor_id,
        0,
        0,
        1,
        4,
        block_sizes=lambda m, r: payload_len,
        sender_of=lambda m: 1,
        holders_of=holders_of,
        replica_of=replica_of,
        **kw,
    )


class TestSpreadAndHedgeTargets:
    def test_spread_rotation_is_deterministic_per_reader(self):
        holders = {1: [1, 2, 3]}
        r5 = _bare_reader(5, holders_of=lambda p, sid: holders[p])
        r6 = _bare_reader(6, holders_of=lambda p, sid: holders[p])
        bid = ShuffleBlockId(0, 2, 0)
        # (executor + map + reduce) % len: reader 5 -> holders[1]=2,
        # reader 6 -> holders[2]=3 — neighbors land on different holders
        assert r5._spread_target(bid) == 2
        assert r6._spread_target(bid) == 3
        assert r5._spread_target(bid) == r5._spread_target(bid)  # stable

    def test_spread_falls_back_to_primary(self):
        bid = ShuffleBlockId(0, 0, 0)
        assert _bare_reader(5)._spread_target(bid) == 1  # no holders_of
        r = _bare_reader(5, holders_of=lambda p, sid: [1])
        assert r._spread_target(bid) == 1  # singleton set: primary
        r = _bare_reader(5, holders_of=lambda p, sid: (_ for _ in ()).throw(TransportError("x")))
        assert r._spread_target(bid) == 1  # pull failure: primary

    def test_spread_never_targets_self(self):
        r = _bare_reader(2, holders_of=lambda p, sid: [1, 2, 3])
        for m in range(4):
            for rid in range(4):
                assert r._spread_target(ShuffleBlockId(0, m, rid)) != 2

    def test_hedge_prefers_non_actual_holder(self):
        """Satellite contract: with >1 holder the hedge goes to a DIFFERENT
        executor than the straggling fetch actually targeted — pinned to the
        deterministic rotation over the admissible candidates."""
        r = _bare_reader(
            5,
            holders_of=lambda p, sid: [1, 2, 3],
            replica_of=lambda p: ring_neighbors(p, [1, 2, 3], 1),
        )
        bid = ShuffleBlockId(0, 2, 0)
        actual = r._spread_target(bid)  # reader 5 -> holder 2
        assert actual == 2
        r._window_targets[bid] = actual
        hedges = {}
        r._issue_hedges([(bid, None, _FakeReq())], hedges)
        assert 0 in hedges
        _, _, target = hedges[0]
        # admissible = [1, 3] (holders minus the actual target); rotation
        # (5 + 2 + 0) % 2 = 1 -> executor 3
        assert target == 3
        assert target != actual
        assert r.transport.hedged_to == [3]
        assert r.metrics.hedges_issued == 1

    def test_hedge_falls_back_to_ring_when_no_advertisement(self):
        r = _bare_reader(
            0, replica_of=lambda p: ring_neighbors(p, [0, 1, 2], 1)
        )
        bid = ShuffleBlockId(0, 0, 0)  # primary 1, actual 1, ring successor 2
        hedges = {}
        r._issue_hedges([(bid, None, _FakeReq())], hedges)
        assert hedges[0][2] == 2

    def test_hedge_never_races_actual_target_or_self(self):
        r = _bare_reader(
            3, holders_of=lambda p, sid: [1, 3], replica_of=lambda p: [3]
        )
        bid = ShuffleBlockId(0, 0, 0)
        r._window_targets[bid] = 1
        hedges = {}
        # candidates reduce to {1 (actual), 3 (self)}: nothing admissible
        r._issue_hedges([(bid, None, _FakeReq())], hedges)
        assert hedges == {}


# ---------------------------------------------------------------------------
# encoded-chunk pool counters (LRU details live in test_compress.py)
# ---------------------------------------------------------------------------


class TestEncodedPoolCounters:
    def test_hit_miss_eviction_counters_export(self):
        ts = _cluster(2, wire_compress_codec="rle")
        try:
            payloads = _stage(ts[0], 1, 1, 2, seed=3)
            ts[0].store.seal(1)
            for _ in range(2):
                for (m, r), p in sorted(payloads.items()):
                    assert _fetch_one(ts[1], 0, 1, m, r, len(p)) == p
            snap = ts[0].server.compress_snapshot()
            assert snap["cache_misses"] >= 2  # first pass encodes
            assert snap["cache_hits"] >= 2  # second pass serves the pool
            assert snap["cache_evictions"] == 0  # default cap: no pressure
            # and the counters ride the existing compress metrics family
            text = ts[0].metrics.prometheus_text()
            assert "compress" in text and "cache_misses" in text
        finally:
            _close_all(ts)

    def test_cache_bytes_zero_disables_pool(self):
        ts = _cluster(2, wire_compress_codec="rle", compress_cache_bytes=0)
        try:
            payloads = _stage(ts[0], 1, 1, 1, seed=4)
            ts[0].store.seal(1)
            p = payloads[(0, 0)]
            assert _fetch_one(ts[1], 0, 1, 0, 0, len(p)) == p
            assert _fetch_one(ts[1], 0, 1, 0, 0, len(p)) == p
            snap = ts[0].server.compress_snapshot()
            assert snap["cache_hits"] == 0  # pool off: every fetch re-encodes
            assert len(ts[0].server._encoded_pool) == 0
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# the lifecycle: storm -> promote -> widen -> spread -> cool -> demote
# ---------------------------------------------------------------------------


def _serve_cluster(n=4, **kw):
    kw.setdefault("replication_factor", 1)
    # 1 fetch/sec: any back-to-back loopback storm promotes even on a
    # heavily loaded CI worker, while one-shot fetches stay cold
    kw.setdefault("serve_hot_threshold_fetches_per_sec", 1.0)
    kw.setdefault("serve_hot_replicas", 2)
    kw.setdefault("serve_cache_bytes", 1 << 20)
    return _cluster(n, **kw)


class TestPopularityLifecycle:
    def test_storm_promotes_widens_and_serves_bit_identical(self):
        ts = _serve_cluster()
        try:
            payloads = _stage(ts[0], 0, 1, 2, seed=11)
            ts[0].store.seal(0)
            assert ts[0].replication_wait(0, timeout=10.0)
            # fault-tolerance floor: base ring successor (executor 1) only
            assert ts[1].store.replica_view(0, 0, 0) is not None
            assert ts[2].store.replica_view(0, 0, 0) is None

            p = payloads[(0, 0)]
            got = _storm(ts[3], 0, 0, 0, 0, len(p))
            assert got == p  # storm payloads bit-identical throughout

            assert ts[0].popularity.is_hot(0)
            snap = ts[0]._serve_view()
            assert snap["promotions"] >= 1 and snap["advertised_hot_shuffles"] == 1

            # the widen push replicated the round onto the EXTRA holder
            assert ts[0].replication_wait(0, timeout=10.0)
            assert ts[2].store.replica_view(0, 0, 0) is not None

            # the primary advertises the full holder set over HOT_SET_PULL
            assert ts[3].hot_holders(0, 0) == [0, 1, 2]

            # every advertised holder serves the block bit-identically
            for holder in (1, 2):
                assert _fetch_one(ts[3], holder, 0, 0, 0, len(p)) == p
        finally:
            _close_all(ts)

    def test_hot_block_pins_in_serve_cache(self):
        ts = _serve_cluster()
        try:
            payloads = _stage(ts[0], 0, 1, 1, seed=12)
            ts[0].store.seal(0)
            p = payloads[(0, 0)]
            assert _storm(ts[3], 0, 0, 0, 0, len(p), rounds=8) == p
            snap = ts[0].store.serve_cache.snapshot()
            assert snap["cache_entries"] >= 1  # admitted on promotion
            assert snap["cache_hits"] >= 1  # later storm fetches hit it
            assert snap["cache_used_bytes"] == len(p)
        finally:
            _close_all(ts)

    def test_readers_spread_load_across_holders(self):
        ts = _serve_cluster()
        try:
            num_reducers = 6
            payloads = _stage(ts[0], 0, 1, num_reducers, seed=13)
            ts[0].store.seal(0)
            assert ts[0].replication_wait(0, timeout=10.0)
            for r in range(num_reducers):
                _storm(ts[3], 0, 0, 0, r, len(payloads[(0, r)]), rounds=4)
            assert ts[0].replication_wait(0, timeout=10.0)  # widen settled
            assert ts[3].hot_holders(0, 0) == [0, 1, 2]

            reader = TpuShuffleReader(
                ts[3],
                executor_id=3,
                shuffle_id=0,
                start_partition=0,
                end_partition=num_reducers,
                num_mappers=1,
                block_sizes=lambda m, r: len(payloads[(m, r)]),
                max_blocks_per_request=2,
                sender_of=lambda m: 0,
                holders_of=ts[3].hot_holders,
                fetch_retries=2,
                fetch_deadline_ms=5000,
                fetch_backoff_ms=10,
            )
            got = {}
            for blk in reader.fetch_blocks():
                got[(blk.block_id.map_id, blk.block_id.reduce_id)] = bytes(blk.data)
                blk.release()
            assert got == payloads  # spread fetches stay bit-identical
            # the rotation actually used more than one holder
            assert len(set(reader._window_targets.values())) > 1
            assert set(reader._window_targets.values()) <= {0, 1, 2}
        finally:
            _close_all(ts)

    def test_cool_down_demotes_and_drops_advertisement(self):
        ts = _serve_cluster()
        try:
            payloads = _stage(ts[0], 0, 1, 1, seed=14)
            ts[0].store.seal(0)
            p = payloads[(0, 0)]
            _storm(ts[3], 0, 0, 0, 0, len(p))
            assert ts[0].popularity.is_hot(0)
            assert ts[3].hot_holders(0, 0)

            # silence, observed through a shifted clock: the sweep demotes
            pop = ts[0].popularity
            real = time.monotonic_ns
            pop._now_ns = lambda: real() + 120 * 1_000_000_000
            ts[0].server.sweep_popularity()
            assert not pop.is_hot(0)
            assert pop.snapshot()["demotions"] >= 1
            assert ts[0]._serve_view()["advertised_hot_shuffles"] == 0

            # past the reader-side TTL the advertisement is gone...
            time.sleep(ts[3].conf.serve_holders_ttl_ms / 1e3 + 0.1)
            assert ts[3].hot_holders(0, 0) == []
            # ...but the widened replicas persist (never below the floor),
            # and the primary still serves the block bit-identically
            assert ts[2].store.replica_view(0, 0, 0) is not None
            assert _fetch_one(ts[3], 0, 0, 0, 0, len(p)) == p
        finally:
            _close_all(ts)

    def test_defaults_off_no_advertisement_no_tracking(self):
        ts = _cluster(3, replication_factor=1)
        try:
            payloads = _stage(ts[0], 0, 1, 1, seed=15)
            ts[0].store.seal(0)
            assert ts[0].replication_wait(0, timeout=10.0)
            p = payloads[(0, 0)]
            assert _storm(ts[2], 0, 0, 0, 0, len(p)) == p
            assert ts[0].popularity is None  # nothing tracked
            assert ts[0]._serve_view() == {}
            assert ts[2].hot_holders(0, 0) == []
            assert ts[2].store.replica_view(0, 0, 0) is None  # no widen push
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# chaos lane: one hot-block holder dies mid-storm
# ---------------------------------------------------------------------------


class TestHotHolderChaos:
    def test_holder_killed_mid_storm_reads_stay_bit_identical(self):
        ts = _serve_cluster(wire_timeout_ms=3000)
        try:
            num_reducers = 6
            payloads = _stage(ts[0], 0, 1, num_reducers, seed=21)
            ts[0].store.seal(0)
            assert ts[0].replication_wait(0, timeout=10.0)
            for r in range(num_reducers):
                _storm(ts[3], 0, 0, 0, r, len(payloads[(0, r)]), rounds=4)
            assert ts[0].replication_wait(0, timeout=10.0)
            assert ts[3].hot_holders(0, 0) == [0, 1, 2]

            # one widened holder dies mid-storm; spread fetches that land on
            # it fail over through the reader's retry/failover path
            faults.kill_executor(ts[2])
            reader = TpuShuffleReader(
                ts[3],
                executor_id=3,
                shuffle_id=0,
                start_partition=0,
                end_partition=num_reducers,
                num_mappers=1,
                block_sizes=lambda m, r: len(payloads[(m, r)]),
                max_blocks_per_request=1,
                sender_of=lambda m: 0,
                holders_of=ts[3].hot_holders,
                replica_of=lambda primary: ring_neighbors(primary, [0, 1, 2, 3], 1),
                fetch_retries=3,
                fetch_deadline_ms=3000,
                fetch_backoff_ms=10,
            )
            got = {}
            for blk in reader.fetch_blocks():
                got[(blk.block_id.map_id, blk.block_id.reduce_id)] = bytes(blk.data)
                blk.release()
            assert got == payloads  # graceful degradation, bit-identical
        finally:
            _close_all(ts)


# ---------------------------------------------------------------------------
# placement helper
# ---------------------------------------------------------------------------


class TestWidenedRingNeighbors:
    def test_base_plus_extra_partition(self):
        members = [0, 1, 2, 3, 4]
        base, extra = widened_ring_neighbors(0, members, 1, 3)
        assert base == [1] and extra == [2, 3]
        assert base == ring_neighbors(0, members, 1)

    def test_hot_factor_never_narrows_below_floor(self):
        members = [0, 1, 2, 3]
        base, extra = widened_ring_neighbors(0, members, 2, 1)
        assert base == [1, 2] and extra == []

    def test_degenerate_rings(self):
        assert widened_ring_neighbors(0, [0], 1, 4) == ([], [])
        assert widened_ring_neighbors(9, [0, 1], 1, 4) == ([], [])  # non-member
