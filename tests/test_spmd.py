"""True multi-controller test: two OS processes, each owning CPU devices, run
the collective exchange in lockstep over gloo — the multi-host deployment shape
(one process per TPU host) exercised without TPU hardware.

Covers: jax.distributed bootstrap, driver/executor address exchange for the peer
plane, MapperInfo commit broadcast (AM id 2), the global-mesh collective from
per-process shards, and post-exchange reads vs a deterministic oracle.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = textwrap.dedent(
    """
    import os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, {root!r})
    import numpy as np
    from sparkucx_tpu.config import TpuShuffleConf
    from sparkucx_tpu.parallel.bootstrap import ExecutorEndpoint
    from sparkucx_tpu.transport.spmd import SpmdShuffleExecutor

    pid = int(sys.argv[1]); coord = sys.argv[2]; driver_host, driver_port = sys.argv[3].split(":")
    conf = TpuShuffleConf(
        staging_capacity_per_executor=1 << 20,
        num_slices=int(os.environ.get("TEST_NUM_SLICES", "1")),
        host_recv_mode=os.environ.get("TEST_HOST_RECV_MODE", "array"),
        spill_dir=os.environ.get("TEST_SPILL_DIR") or None,
        slot_quota_rows=int(os.environ.get("TEST_SLOT_QUOTA_ROWS", "0")),
        exchange_impl=os.environ.get("TEST_EXCHANGE_IMPL", "stock"),
    )
    ex = SpmdShuffleExecutor(conf, coordinator_address=coord, num_processes=2, process_id=pid)
    assert ex.num_executors == 2, ex.num_executors
    addr = ex.init()
    ep = ExecutorEndpoint((driver_host, int(driver_port)), ex.executor_id, ex.peer)
    ep.register(addr)
    deadline = time.monotonic() + 30
    other = 1 - pid
    while other not in ep.known and time.monotonic() < deadline:
        time.sleep(0.01)
    assert other in ep.known, "peer never introduced"

    M, R = 4, 4
    ex.create_shuffle(0, M, R)
    def payload(m, r):
        rng = np.random.default_rng(100 * m + r)
        return rng.integers(0, 256, size=int(rng.integers(1, 1500)), dtype=np.uint8).tobytes()

    for m in range(M):
        if ex.map_owner(m) != ex.executor_id:
            continue
        w = ex.store.map_writer(0, m)
        for r in range(R):
            w.write_partition(r, payload(m, r))
        ex.commit_map(w)

    ex.run_exchange(0)

    checked = 0
    for r in range(R):
        if ex.owner_of_reduce(0, r) != ex.executor_id:
            continue
        for m in range(M):
            got = ex.read_received_block(0, m, r)
            assert got == payload(m, r), f"mismatch at map={{m}} reduce={{r}}"
            checked += 1
    assert checked > 0
    if conf.host_recv_mode == "memmap":
        # the received rounds live on disk, not RAM, and are reclaimed
        shards, _ = ex._recv[0]
        assert shards and all(isinstance(s, np.memmap) for s in shards)
        spilled = list(ex._recv_spill.get(0, []))
        assert spilled and all(os.path.exists(p) for p, _ in spilled)
        # the refund is the charged nbytes, not getsize: budget returns to 0
        assert ex._recv_spill_bytes == sum(nb for _, nb in spilled)
        ex.remove_shuffle(0)
        assert not any(os.path.exists(p) for p, _ in spilled), "spmd spill leaked"
        assert ex._recv_spill_bytes == 0, "spill budget not fully refunded"
    print(f"CHILD_PASS pid={{pid}} checked={{checked}}", flush=True)
    ex.close(); ep.close()
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_spmd_exchange():
    from sparkucx_tpu.parallel.bootstrap import DriverEndpoint

    driver = DriverEndpoint()
    coord = f"127.0.0.1:{_free_port()}"
    driver_addr = f"{driver.address[0]}:{driver.address[1]}"
    env = {k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    script = CHILD.format(root=ROOT)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(pid), coord, driver_addr],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=ROOT, env=env,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"child {pid} failed:\n{out[-3000:]}"
            assert f"CHILD_PASS pid={pid}" in out, out[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        driver.close()


def test_two_process_spmd_exchange_two_slices():
    """Multi-host AND multi-slice: each process is one slice of one chip; the
    superstep routes through the two-phase hierarchy over jax.distributed."""
    from sparkucx_tpu.parallel.bootstrap import DriverEndpoint

    driver = DriverEndpoint()
    coord = f"127.0.0.1:{_free_port()}"
    driver_addr = f"{driver.address[0]}:{driver.address[1]}"
    env = {k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["TEST_NUM_SLICES"] = "2"
    script = CHILD.format(root=ROOT)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(pid), coord, driver_addr],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=ROOT, env=env,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"child {pid} failed:\n{out[-3000:]}"
            assert f"CHILD_PASS pid={pid}" in out, out[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        driver.close()


def test_two_process_spmd_exchange_quota():
    """Multi-controller + slotQuotaRows: both processes must all-gather the
    same sub-round plan (lockstep collectives) and splice chunked receive
    bytes back to the oracle.  Quota of 1 row with ≤1500-byte payloads (3
    rows at 512 alignment) forces 3 sub-rounds per staging round."""
    from sparkucx_tpu.parallel.bootstrap import DriverEndpoint

    driver = DriverEndpoint()
    coord = f"127.0.0.1:{_free_port()}"
    driver_addr = f"{driver.address[0]}:{driver.address[1]}"
    env = {k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["TEST_SLOT_QUOTA_ROWS"] = "1"
    script = CHILD.format(root=ROOT)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(pid), coord, driver_addr],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=ROOT, env=env,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"child {pid} failed:\n{out[-3000:]}"
            assert f"CHILD_PASS pid={pid}" in out, out[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        driver.close()


def test_two_process_spmd_exchange_memmap(tmp_path):
    """Multi-controller + host_recv_mode='memmap': each process spills its
    received rounds to read-only disk mappings (the per-host memory budget of
    transport/tpu.py's memmap mode) and reclaims them on remove_shuffle."""
    from sparkucx_tpu.parallel.bootstrap import DriverEndpoint

    driver = DriverEndpoint()
    coord = f"127.0.0.1:{_free_port()}"
    driver_addr = f"{driver.address[0]}:{driver.address[1]}"
    env = {k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["TEST_HOST_RECV_MODE"] = "memmap"
    env["TEST_SPILL_DIR"] = str(tmp_path)
    script = CHILD.format(root=ROOT)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(pid), coord, driver_addr],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=ROOT, env=env,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"child {pid} failed:\n{out[-3000:]}"
            assert f"CHILD_PASS pid={pid}" in out, out[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        driver.close()
