"""The stub-fidelity lint (scripts/check_stub_fidelity.py) — the no-JDK
surrogate for the javac gate (VERDICT r4 task 3): the real tree must pass, and
seeded drift between ``jvm/src`` and ``jvm/stubs`` must be caught."""

import os
import shutil
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(ROOT, "scripts")
sys.path.insert(0, SCRIPTS)

import check_stub_fidelity as fidelity  # noqa: E402


def run_on(stub_dir, src_dir):
    """Run the checker against alternate trees; returns (rc, messages)."""
    old = fidelity.STUB_DIR, fidelity.SRC_DIR
    import io
    from contextlib import redirect_stdout

    fidelity.STUB_DIR, fidelity.SRC_DIR = str(stub_dir), str(src_dir)
    buf = io.StringIO()
    try:
        with redirect_stdout(buf):
            try:
                rc = fidelity.main()
            except SystemExit as e:  # load_stubs exits on stub-layout errors
                rc = e.code
    finally:
        fidelity.STUB_DIR, fidelity.SRC_DIR = old
    return rc, buf.getvalue()


@pytest.fixture
def fault_tree(tmp_path):
    """A private copy of jvm/ to seed faults into."""
    shutil.copytree(os.path.join(ROOT, "jvm", "stubs"), tmp_path / "stubs")
    shutil.copytree(os.path.join(ROOT, "jvm", "src"), tmp_path / "src")
    return tmp_path


def _edit(path, old, new):
    text = path.read_text()
    assert old in text, f"fault seed {old!r} not found in {path}"
    path.write_text(text.replace(old, new))


MANAGER_STUB = "stubs/org/apache/spark/shuffle/ShuffleManager.java"
MANAGER_SRC = "src/org/apache/spark/shuffle/tpu/TpuShuffleManager.java"


class TestRealTreePasses:
    def test_checked_in_tree_is_clean(self):
        rc = subprocess.run(
            [sys.executable, os.path.join(SCRIPTS, "check_stub_fidelity.py")],
            capture_output=True, text=True, cwd=ROOT,
        )
        assert rc.returncode == 0, rc.stdout + rc.stderr
        assert "STUB FIDELITY: OK" in rc.stdout


class TestSeededFaultsAreCaught:
    def test_renamed_spi_method_in_stub(self, fault_tree):
        _edit(fault_tree / MANAGER_STUB,
              "boolean unregisterShuffle(int shuffleId);",
              "boolean unregisterShuffleX(int shuffleId);")
        rc, out = run_on(fault_tree / "stubs", fault_tree / "src")
        assert rc == 1
        assert "lacks unregisterShuffleX" in out

    def test_typoed_call_on_stub_receiver(self, fault_tree):
        _edit(fault_tree / MANAGER_SRC,
              "dependency.rdd().getNumPartitions(),",
              "dependency.rddX().getNumPartitions(),")
        rc, out = run_on(fault_tree / "stubs", fault_tree / "src")
        assert rc == 1
        assert "rddX() not declared by stub" in out

    def test_wrong_call_arity(self, fault_tree):
        _edit(fault_tree / MANAGER_SRC,
              'conf.getInt("spark.shuffle.tpu.daemon.port", 1338)',
              'conf.getInt("spark.shuffle.tpu.daemon.port")')
        rc, out = run_on(fault_tree / "stubs", fault_tree / "src")
        assert rc == 1
        assert "getInt() called with 1 args" in out

    def test_chain_hop_typo(self, fault_tree):
        _edit(fault_tree / MANAGER_SRC,
              "dependency.partitioner().numPartitions());",
              "dependency.partitioner().numPartitionsX());")
        rc, out = run_on(fault_tree / "stubs", fault_tree / "src")
        assert rc == 1
        assert "numPartitionsX" in out

    def test_missing_stub_for_import(self, fault_tree):
        os.unlink(fault_tree / "stubs/org/apache/spark/storage/BlockManagerId.java")
        rc, out = run_on(fault_tree / "stubs", fault_tree / "src")
        assert rc == 1
        assert "import org.apache.spark.storage.BlockManagerId has no stub" in out

    def test_stub_package_mismatch(self, fault_tree):
        _edit(fault_tree / MANAGER_STUB,
              "package org.apache.spark.shuffle;",
              "package org.apache.spark.wrong;")
        rc, out = run_on(fault_tree / "stubs", fault_tree / "src")
        assert rc == 1
        assert "package" in out
