"""Tests for the loopback transport: registry, deferred fetch, staged store."""

import numpy as np
import pytest

from sparkucx_tpu.core.block import BytesBlock, MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.definitions import MapperInfo
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.core.transport import ShuffleTransport
from sparkucx_tpu.transport.loopback import LoopbackFabric, LoopbackTransport


@pytest.fixture
def pair():
    fabric = LoopbackFabric()
    a = LoopbackTransport(executor_id=1, fabric=fabric)
    b = LoopbackTransport(executor_id=2, fabric=fabric)
    addr_a, addr_b = a.init(), b.init()
    a.add_executor(2, addr_b)
    b.add_executor(1, addr_a)
    yield a, b
    a.close()
    b.close()


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


class TestRegistry:
    def test_register_fetch_roundtrip(self, pair):
        a, b = pair
        bid = ShuffleBlockId(0, 1, 2)
        b.register(bid, BytesBlock(b"payload-123"))
        out = _buf(64)
        results = []
        [req] = a.fetch_blocks_by_block_ids(2, [bid], [out], [results.append])
        # progress() contract: nothing completes until polled
        assert not req.completed() and not results
        while not req.completed():
            a.progress()
        res = req.wait(1)
        assert res.status == OperationStatus.SUCCESS
        assert out.host_view()[:11].tobytes() == b"payload-123"
        assert results and results[0].stats.recv_size == 11

    def test_fetch_missing_block_fails(self, pair):
        a, b = pair
        out = _buf(16)
        [req] = a.fetch_blocks_by_block_ids(2, [ShuffleBlockId(9, 9, 9)], [out], [None])
        while not req.completed():
            a.progress()
        assert req.wait(1).status == OperationStatus.FAILURE

    def test_fetch_unknown_executor_fails(self, pair):
        a, _ = pair
        [req] = a.fetch_blocks_by_block_ids(42, [ShuffleBlockId(0, 0, 0)], [_buf(4)], [None])
        while not req.completed():
            a.progress()
        assert req.wait(1).status == OperationStatus.FAILURE

    def test_oversized_block_fails_cleanly(self, pair):
        # A payload larger than the result buffer must complete as FAILURE,
        # not leave the request hanging.
        a, b = pair
        bid = ShuffleBlockId(0, 0, 0)
        b.register(bid, BytesBlock(b"x" * 100))
        [req] = a.fetch_blocks_by_block_ids(2, [bid], [_buf(16)], [None])
        while not req.completed():
            a.progress()
        res = req.wait(1)
        assert res.status == OperationStatus.FAILURE
        assert "exceeds result buffer" in str(res.error)

    def test_close_cancels_pending(self):
        fabric = LoopbackFabric()
        a = LoopbackTransport(executor_id=1, fabric=fabric)
        a.init()
        [req] = a.fetch_blocks_by_block_ids(1, [ShuffleBlockId(0, 0, 0)], [_buf(4)], [None])
        a.close()
        assert req.wait(1).status == OperationStatus.CANCELED

    def test_mutate_swaps_under_lock(self, pair):
        a, b = pair
        bid = ShuffleBlockId(0, 0, 0)
        b.register(bid, BytesBlock(b"old"))
        done = []
        b.mutate(bid, BytesBlock(b"new"), done.append)
        assert done[0].status == OperationStatus.SUCCESS
        out = _buf(8)
        [req] = a.fetch_blocks_by_block_ids(2, [bid], [out], [None])
        while not req.completed():
            a.progress()
        assert out.host_view()[:3].tobytes() == b"new"

    def test_unregister_shuffle_bulk(self, pair):
        _, b = pair
        for r in range(4):
            b.register(ShuffleBlockId(5, 0, r), BytesBlock(b"x"))
        b.register(ShuffleBlockId(6, 0, 0), BytesBlock(b"y"))
        b.unregister_shuffle(5)
        assert b.registered_block(ShuffleBlockId(5, 0, 1)) is None
        assert b.registered_block(ShuffleBlockId(6, 0, 0)) is not None

    def test_batch_fetch(self, pair):
        a, b = pair
        payloads = {r: bytes([r]) * (r + 1) for r in range(8)}
        for r, p in payloads.items():
            b.register(ShuffleBlockId(1, 0, r), BytesBlock(p))
        bids = [ShuffleBlockId(1, 0, r) for r in range(8)]
        bufs = [_buf(16) for _ in range(8)]
        reqs = a.fetch_blocks_by_block_ids(2, bids, bufs, [None] * 8)
        while not all(r.completed() for r in reqs):
            a.progress()
        for r in range(8):
            assert bufs[r].host_view()[: r + 1].tobytes() == payloads[r]


class TestStagedStore:
    def test_staged_fetch(self, pair):
        a, b = pair
        b.store_write(3, 1, 0, b"staged-bytes")
        out = _buf(64)
        req = a.fetch_block(2, 3, 1, 0, out)
        while not req.completed():
            a.progress()
        res = req.wait(1)
        assert res.status == OperationStatus.SUCCESS
        assert out.size == 12
        assert out.host_view()[:12].tobytes() == b"staged-bytes"

    def test_commit_block_validates(self, pair):
        a, _ = pair
        done = []
        blob = MapperInfo(1, 0, ((0, 10), (16, 6))).pack()
        a.commit_block(blob, done.append)
        assert done[0].status == OperationStatus.SUCCESS

    def test_unregister_shuffle_clears_store(self, pair):
        a, b = pair
        b.store_write(7, 0, 0, b"z")
        b.unregister_shuffle(7)
        req = a.fetch_block(2, 7, 0, 0, _buf(8))
        while not req.completed():
            a.progress()
        assert req.wait(1).status == OperationStatus.FAILURE


def test_is_transport_subclass():
    assert issubclass(LoopbackTransport, ShuffleTransport)
