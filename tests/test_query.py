"""Query DAG runner + lineage-keyed cross-query shuffle reuse (sparkucx_tpu/query).

Three concerns:

* the runner composes the existing manager SPI into whole pipelines whose
  results match the pure-CPU oracles (groupby / terasort / join shapes),
* the lineage hash keys exactly the byte-affecting tiers — property tests
  cross-checked against the analyzer's COLLECTIVE/SERVE_PLANE registries so
  the two views of "what shapes the bytes" cannot drift,
* the cache lifecycle: hits are bit-identical and skip the exchange, entries
  die on input-fingerprint change or ``unregister_shuffle`` (every serve tier
  included), admission charges the owning tenant, and quota pressure
  recomputes largest-footprint entries first (arXiv:2112.01075).
"""

import dataclasses

import pytest

from sparkucx_tpu.analysis.config import COLLECTIVE_FIELDS, SERVE_PLANE_FIELDS
from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.ops.skew import ExchangePlan
from sparkucx_tpu.query import (
    BYTE_AFFECTING_PLAN_FIELDS,
    SCHEDULE_ONLY_PLAN_FIELDS,
    SERVE_ONLY_PLAN_FIELDS,
    LineageCache,
    QueryRunner,
    Stage,
    StageDag,
    conf_byte_signature,
    lineage_key,
    plan_byte_signature,
)
from sparkucx_tpu.service.eviction import EvictionManager
from sparkucx_tpu.service.tenants import TenantRegistry
from sparkucx_tpu.shuffle.manager import TpuShuffleManager

N_EXEC = 4


def _conf(**kw):
    kw.setdefault("staging_capacity_per_executor", 1 << 20)
    kw.setdefault("num_executors", N_EXEC)
    return TpuShuffleConf(**kw)


def _groupby_dag():
    return StageDag(
        [
            Stage.make("src", "scan"),
            Stage.make("ex", "exchange", ["src"]),
            Stage.make("agg", "aggregate", ["ex"]),
        ]
    )


def _rows(n=600, keys=40, salt=0):
    return [(i % keys, i + salt) for i in range(n)]


def _sum_oracle(rows):
    out = {}
    for k, v in rows:
        out[k] = out.get(k, 0) + v
    return out


# ---------------------------------------------------------------------------
# StageDag
# ---------------------------------------------------------------------------


class TestStageDag:
    def test_validation(self):
        with pytest.raises(ValueError, match="empty"):
            StageDag([])
        with pytest.raises(ValueError, match="unknown op"):
            StageDag([Stage.make("a", "scan"), Stage.make("b", "mapreduce", ["a"])])
        with pytest.raises(ValueError, match="duplicate"):
            StageDag([Stage.make("a", "scan"), Stage.make("a", "scan")])
        with pytest.raises(ValueError, match="undefined"):
            StageDag([Stage.make("e", "exchange", ["ghost"])])
        with pytest.raises(ValueError, match="takes 2 input"):
            StageDag([Stage.make("a", "scan"), Stage.make("j", "join", ["a"])])
        with pytest.raises(ValueError, match="takes 0 input"):
            StageDag([Stage.make("a", "scan"), Stage.make("b", "scan", ["a"])])

    def test_canonical_is_deterministic_and_scoped(self):
        dag = StageDag(
            [
                Stage.make("b", "scan"),
                Stage.make("p", "scan"),
                Stage.make("eb", "exchange", ["b"]),
                Stage.make("ep", "exchange", ["p"]),
                Stage.make("j", "join", ["eb", "ep"]),
            ]
        )
        assert dag.canonical("eb") == dag.canonical("eb")
        # the sub-DAG rooted at eb does not include the probe side
        assert '"p"' not in dag.canonical("eb")
        assert '"p"' in dag.canonical("j")
        # scan fingerprints enter the serialization (and only under the root)
        fps = {"b": "aa", "p": "bb"}
        assert dag.canonical("eb", fps) != dag.canonical("eb")
        assert "bb" not in dag.canonical("eb", fps)

    def test_params_affect_canonical(self):
        d1 = StageDag([Stage.make("s", "scan"), Stage.make("e", "exchange", ["s"])])
        d2 = StageDag(
            [Stage.make("s", "scan"), Stage.make("e", "exchange", ["s"], partitions=2)]
        )
        assert d1.canonical("e") != d2.canonical("e")


# ---------------------------------------------------------------------------
# lineage hash property tests (cross-checked vs the analyzer registries)
# ---------------------------------------------------------------------------

#: conf knob -> value that flips each byte-affecting tier
_BYTE_TIER_CONFS = {
    "wire_compress_codec": "rle",  # spark.shuffle.tpu.compress.codec
    "quantize_mode": "int8",  # spark.shuffle.tpu.quantize.mode
    "quantize_block_size": 64,  # spark.shuffle.tpu.quantize.blockSize
    "exchange_fused_combine": True,  # spark.shuffle.tpu.exchange.fusedCombine
}

#: serve-plane-only knobs: tune serving/overlap, never the bytes
_SERVE_TIER_CONFS = {
    "fetch_hedge_ms": 7,  # spark.shuffle.tpu.fetch.hedgeMs
    "wire_streams": 4,  # spark.shuffle.tpu.wire.streams
    "pipeline_depth": 5,  # spark.shuffle.tpu.pipelineDepth
}


class TestLineageRegistryAlignment:
    """The partition of ExchangePlan fields used by the lineage key must stay
    exactly the analyzer's COLLECTIVE/SERVE_PLANE vocabulary — a new plan
    field, or a field moving between registries, fails here."""

    def test_partition_is_total_and_disjoint(self):
        plan_fields = {f.name for f in dataclasses.fields(ExchangePlan)}
        byte, sched, serve = (
            set(BYTE_AFFECTING_PLAN_FIELDS),
            set(SCHEDULE_ONLY_PLAN_FIELDS),
            set(SERVE_ONLY_PLAN_FIELDS),
        )
        assert byte | sched | serve == plan_fields
        assert not (byte & sched) and not (byte & serve) and not (sched & serve)

    def test_derived_from_analyzer_registries(self):
        assert set(SCHEDULE_ONLY_PLAN_FIELDS) <= set(COLLECTIVE_FIELDS)
        assert set(SERVE_ONLY_PLAN_FIELDS) <= set(SERVE_PLANE_FIELDS)
        assert set(BYTE_AFFECTING_PLAN_FIELDS) <= set(COLLECTIVE_FIELDS) | set(
            SERVE_PLANE_FIELDS
        )
        # the byte tiers are exactly the lossy/content fields the ISSUE names
        assert set(BYTE_AFFECTING_PLAN_FIELDS) == {
            "codec",
            "quantize_mode",
            "quantize_block",
            "combine",
        }

    def test_conf_signature_speaks_plan_vocabulary(self):
        import json

        assert set(json.loads(conf_byte_signature(_conf()))) == set(
            BYTE_AFFECTING_PLAN_FIELDS
        )


class TestLineageKeyProperties:
    def setup_method(self):
        self.dag = _groupby_dag()
        self.fps = {"src": "f" * 64}

    def _key(self, conf):
        return lineage_key(self.dag, "ex", self.fps, conf)

    @pytest.mark.parametrize("field,value", sorted(_BYTE_TIER_CONFS.items()))
    def test_byte_affecting_tiers_change_the_key(self, field, value):
        base = self._key(_conf())
        assert self._key(_conf(**{field: value})) != base

    @pytest.mark.parametrize("field,value", sorted(_SERVE_TIER_CONFS.items()))
    def test_serve_plane_tiers_do_not(self, field, value):
        base = self._key(_conf())
        assert self._key(_conf(**{field: value})) == base

    def test_fingerprint_and_structure_change_the_key(self):
        conf = _conf()
        base = self._key(conf)
        assert lineage_key(self.dag, "ex", {"src": "0" * 64}, conf) != base
        wider = StageDag(
            [
                Stage.make("src", "scan"),
                Stage.make("ex", "exchange", ["src"], partitions=2),
            ]
        )
        assert lineage_key(wider, "ex", self.fps, conf) != base

    def test_plan_byte_signature_ignores_schedule_and_serve_fields(self):
        base = ExchangePlan(slot_rows=64, chunks_per_round=(2, 2))
        sig = plan_byte_signature(base)
        for variant in (
            dataclasses.replace(base, slot_rows=128),
            dataclasses.replace(base, chunks_per_round=(4,)),
            dataclasses.replace(base, single_shot=True),
            dataclasses.replace(base, round_order=(1, 0)),
            dataclasses.replace(base, lowering="pallas"),
            dataclasses.replace(base, pipeline_depth=7),
            dataclasses.replace(base, streams=8),
            dataclasses.replace(base, hedge_ms=11),
        ):
            assert plan_byte_signature(variant) == sig
        for variant in (
            dataclasses.replace(base, codec="rle"),
            dataclasses.replace(base, quantize_mode="int8"),
            dataclasses.replace(base, quantize_block=32),
            dataclasses.replace(base, combine="dense"),
        ):
            assert plan_byte_signature(variant) != sig


# ---------------------------------------------------------------------------
# runner pipelines + cache lifecycle
# ---------------------------------------------------------------------------


@pytest.fixture()
def cached_manager():
    mgr = TpuShuffleManager(_conf(query_cache_enabled=True), num_executors=N_EXEC)
    yield mgr
    mgr.stop()


class TestQueryRunner:
    def test_groupby_pipeline_and_reuse(self, cached_manager):
        runner = QueryRunner(cached_manager, "appA")
        dag, rows = _groupby_dag(), _rows()
        cold = runner.run(dag, {"src": rows})
        assert {k: v for part in cold for k, v in part} == _sum_oracle(rows)
        warm = runner.run(dag, {"src": rows})
        # the hit is bit-identical AND skipped the exchange entirely
        assert warm == cold
        snap = runner._snapshot()
        assert snap["exchanges_executed"] == 1
        assert snap["exchanges_reused"] == 1
        assert snap["cache_hits"] == 1

    def test_terasort_pipeline(self, cached_manager, rng):
        runner = QueryRunner(cached_manager, "appSort")
        dag = StageDag(
            [
                Stage.make("s", "scan"),
                Stage.make("e", "exchange", ["s"]),
                Stage.make("o", "sort", ["e"]),
            ]
        )
        rows = [(int(k), i) for i, k in enumerate(rng.integers(0, 1 << 20, 500))]
        out = runner.run(dag, {"s": rows})
        assert [k for k, _ in out] == sorted(k for k, _ in rows)
        # same keys AND payloads survive the shuffle
        assert sorted(out) == sorted((k, v) for k, v in rows)

    def test_join_pipeline(self, cached_manager):
        runner = QueryRunner(cached_manager, "appJoin")
        dag = StageDag(
            [
                Stage.make("b", "scan"),
                Stage.make("p", "scan"),
                Stage.make("eb", "exchange", ["b"]),
                Stage.make("ep", "exchange", ["p"]),
                Stage.make("j", "join", ["eb", "ep"]),
            ]
        )
        build = [(i % 10, i) for i in range(30)]
        probe = [(i % 10, 100 + i) for i in range(20)]
        out = runner.run(dag, {"b": build, "p": probe})
        got = sorted(row for part in out for row in part)
        oracle = sorted(
            (k, bv, pv) for k, bv in build for pk, pv in probe if pk == k
        )
        assert got == oracle

    def test_shared_exchange_reused_across_dags(self, cached_manager):
        """Two different queries over the same scan+exchange sub-DAG share
        one sealed shuffle — the cross-QUERY in cross-query reuse."""
        runner = QueryRunner(cached_manager, "appX")
        rows = _rows()
        agg = _groupby_dag()
        srt = StageDag(
            [
                Stage.make("src", "scan"),
                Stage.make("ex", "exchange", ["src"]),
                Stage.make("out", "sort", ["ex"]),
            ]
        )
        runner.run(agg, {"src": rows})
        runner.run(srt, {"src": rows})
        snap = runner._snapshot()
        assert snap["exchanges_executed"] == 1 and snap["exchanges_reused"] == 1

    def test_input_change_invalidates_stale_entry(self, cached_manager):
        cache = LineageCache()
        runner = QueryRunner(cached_manager, "appB", cache=cache)
        dag = _groupby_dag()
        runner.run(dag, {"src": _rows(salt=0)})
        runner.run(dag, {"src": _rows(salt=1)})
        snap = cache.snapshot()
        # the first entry could never hit again: dropped, not leaked
        assert snap["cache_invalidations"] == 1
        assert snap["cached_entries"] == 1
        assert runner._snapshot()["stale_invalidations"] == 1

    def test_external_unregister_invalidates(self, cached_manager):
        cache = LineageCache()
        runner = QueryRunner(cached_manager, "appC", cache=cache)
        dag, rows = _groupby_dag(), _rows()
        cold = runner.run(dag, {"src": rows})
        (sid,) = list(cache._by_sid)
        cached_manager.unregister_shuffle(sid)  # external removal
        assert cache.snapshot()["cached_entries"] == 0
        again = runner.run(dag, {"src": rows})  # re-executes, same bytes
        assert again == cold
        assert runner._snapshot()["exchanges_executed"] == 2

    def test_admission_charges_tenant_and_pressure_evicts_largest(
        self, cached_manager
    ):
        cache = LineageCache()
        tenants = TenantRegistry(default_quota_bytes=0)
        runner = QueryRunner(cached_manager, "appQ", tenants=tenants, cache=cache)
        big, small = _rows(n=800), _rows(n=100, keys=7)
        dag_big = StageDag(
            [Stage.make("big", "scan"), Stage.make("exb", "exchange", ["big"])]
        )
        dag_small = StageDag(
            [Stage.make("small", "scan"), Stage.make("exs", "exchange", ["small"])]
        )
        runner.run(dag_big, {"big": big})
        runner.run(dag_small, {"small": small})
        entries = sorted(cache._entries.values(), key=lambda e: e.nbytes)
        assert len(entries) == 2
        assert tenants.usage("appQ") == sum(e.nbytes for e in entries)
        # shrink the quota so the next admission must free bytes: the
        # LARGEST resident is recomputed first (arXiv:2112.01075 footprint
        # model), the small one stays
        small_entry, big_entry = entries
        tenants.register("appQ", hbm_quota_bytes=tenants.usage("appQ") + 1)
        dag_mid = StageDag(
            [Stage.make("mid", "scan"), Stage.make("exm", "exchange", ["mid"])]
        )
        runner.run(dag_mid, {"mid": _rows(n=400, keys=11)})
        keys_left = set(cache._entries)
        assert ("appQ", big_entry.key) not in keys_left  # largest evicted
        assert ("appQ", small_entry.key) in keys_left  # smallest kept
        assert cache.snapshot()["cache_evictions"] >= 1
        # charge/release stayed balanced through the eviction
        assert tenants.usage("appQ") == sum(e.nbytes for e in cache._entries.values())

    def test_unadmittable_round_runs_uncached(self, cached_manager):
        cache = LineageCache(max_bytes=1)  # spark.shuffle.tpu.query.cacheMaxBytes
        runner = QueryRunner(cached_manager, "appU", cache=cache)
        dag, rows = _groupby_dag(), _rows()
        out = runner.run(dag, {"src": rows})
        assert {k: v for part in out for k, v in part} == _sum_oracle(rows)
        snap = runner._snapshot()
        assert snap["uncached_rounds"] == 1 and snap["cached_entries"] == 0

    def test_query_metrics_family_exported(self, cached_manager):
        runner = QueryRunner(cached_manager, "appM")
        runner.run(_groupby_dag(), {"src": _rows()})
        fams = {s.family for s in cached_manager.cluster.metrics.snapshot()}
        assert "query" in fams
        names = {
            s.name
            for s in cached_manager.cluster.metrics.snapshot()
            if s.family == "query"
        }
        assert {"queries", "cache_hits", "cache_misses", "cached_bytes"} <= names


class TestOffPath:
    def test_cache_disabled_is_cacheless_and_clean(self):
        mgr = TpuShuffleManager(_conf(), num_executors=N_EXEC)
        try:
            assert mgr.conf.query_cache_enabled is False  # default off
            runner = QueryRunner(mgr, "appOff")
            dag, rows = _groupby_dag(), _rows()
            out1 = runner.run(dag, {"src": rows})
            out2 = runner.run(dag, {"src": rows})
            assert out1 == out2
            assert {k: v for part in out1 for k, v in part} == _sum_oracle(rows)
            snap = runner._snapshot()
            # every exchange executed; nothing cached, retained, or charged
            assert snap["exchanges_executed"] == 2
            assert snap["exchanges_reused"] == 0
            assert "cache_hits" not in snap
            assert not mgr._shuffle_dims
        finally:
            mgr.stop()

    def test_conf_knobs_parse_and_validate(self):
        conf = TpuShuffleConf.from_spark_conf(
            {
                "spark.shuffle.tpu.query.cacheEnabled": "true",
                "spark.shuffle.tpu.query.cacheMaxBytes": "64m",
            }
        )
        assert conf.query_cache_enabled is True
        assert conf.query_cache_max_bytes == 64 << 20
        with pytest.raises(ValueError, match="query_cache_max_bytes"):
            TpuShuffleConf(query_cache_max_bytes=-1).validate()


# ---------------------------------------------------------------------------
# no-stale-tier invalidation: the eviction access table (store side)
# ---------------------------------------------------------------------------


class TestEvictionForgetShuffle:
    def test_forget_shuffle_prunes_access_table(self):
        ev = EvictionManager(store=None, restage_on_fetch=False)
        ev.on_access(5, 0)
        ev.on_access(5, 1)
        ev.on_access(6, 0)
        ev.forget_shuffle(5)
        assert set(ev._access) == {(6, 0)}

    def test_store_remove_shuffle_forgets(self):
        mgr = TpuShuffleManager(_conf(), num_executors=N_EXEC)
        try:
            store = mgr.cluster.transports[0].store
            ev = EvictionManager(store=store, restage_on_fetch=False)
            store.eviction = ev
            runner = QueryRunner(mgr, "appEv")
            dag = StageDag(
                [Stage.make("s", "scan"), Stage.make("e", "exchange", ["s"])]
            )
            runner.run(dag, {"s": _rows(n=200)})
            ev.on_access(99, 0)  # unrelated shuffle keeps its clock
            with_reads = [sid for sid, _ in ev._access]
            # the runner's off-path teardown removed its shuffles from the
            # store — and the store told the eviction manager
            assert set(with_reads) == {99}
        finally:
            mgr.stop()
