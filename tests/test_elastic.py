"""Elastic mesh: shrink/regrow with degraded-mode exchange recovery.

Pins the PR's elasticity contracts end to end:

* ``ClusterMembership`` — observation-driven liveness, local epochs,
  debounced suspicion, idempotent transitions,
* ``degraded_plan`` — pow2 shrink + wave decomposition invariants,
* the headline chaos scenario: kill an executor MID-SUPERSTEP at
  ``replication.factor=1`` and the shuffle completes on the surviving pow2
  bucket with every block BIT-IDENTICAL to the no-fault run (stock and
  pallas exchange impls, array and memmap receive modes),
* the no-hang guarantee: factor=0 / elastic-off / double failure all raise
  typed, addressed errors instead of stalling,
* regrow: a rejoined executor restores the full mesh for the next shuffle,
* membership gossip over the peer wire (MEMBER_SUSPECT / MEMBER_REJOIN),
* the SPMD executor's fail-fast guard (degraded view -> typed error before
  the lockstep collective).
"""

import time

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.operation import (
    BlockNotFoundError,
    ExecutorLostError,
)
from sparkucx_tpu.parallel.membership import ClusterMembership
from sparkucx_tpu.shuffle.resolver import degraded_plan
from sparkucx_tpu.testing import faults
from sparkucx_tpu.transport.tpu import TpuShuffleCluster


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# membership units
# ---------------------------------------------------------------------------


class TestClusterMembership:
    def test_initial_state(self):
        m = ClusterMembership(range(4))
        assert m.epoch == 0 and not m.degraded
        assert m.alive() == [0, 1, 2, 3] and m.dead() == {}

    def test_mark_dead_bumps_epoch_once(self):
        m = ClusterMembership(range(4))
        assert m.mark_dead(2, "chaos")
        assert m.epoch == 1 and m.degraded
        assert not m.mark_dead(2, "again")  # idempotent: no re-bump
        assert m.epoch == 1
        assert m.dead() == {2: "chaos"}
        assert m.alive() == [0, 1, 3]

    def test_unknown_ids_absorbed(self):
        m = ClusterMembership(range(2))
        assert not m.mark_dead(9, "who?")
        assert not m.mark_alive(9)
        assert not m.suspect(9, "noise")
        assert m.epoch == 0

    def test_rejoin_bumps_epoch(self):
        m = ClusterMembership(range(3))
        m.mark_dead(1, "down")
        assert m.mark_alive(1)
        assert m.epoch == 2 and not m.degraded
        assert not m.mark_alive(1)  # already alive
        assert m.epoch == 2

    def test_suspect_without_debounce_kills_first_error(self):
        m = ClusterMembership(range(3), suspect_after_ms=0)
        assert m.suspect(2, "RST")
        assert m.dead() == {2: "RST"}

    def test_suspect_debounce_window(self):
        m = ClusterMembership(range(3), suspect_after_ms=10_000)
        assert not m.suspect(2, "first error")  # inside the window
        assert m.is_alive(2) and m.epoch == 0
        assert not m.suspect(2, "second error, still inside")
        assert m.is_alive(2)

    def test_suspect_debounce_expiry_kills(self):
        m = ClusterMembership(range(3), suspect_after_ms=20)
        assert not m.suspect(2, "first")
        time.sleep(0.05)
        assert m.suspect(2, "persisted")
        assert not m.is_alive(2)

    def test_snapshot_is_consistent(self):
        m = ClusterMembership(range(4))
        m.mark_dead(3, "gone")
        snap = m.snapshot()
        assert snap == {"epoch": 1, "alive": [0, 1, 2], "dead": {3: "gone"}}

    def test_liveness_observation_clears_pending_suspicion(self):
        """A gray peer that recovers inside the debounce window must restart
        suspicion from scratch: mark_alive on an already-alive executor pops
        the pending suspect entry (no epoch bump), so the NEXT error opens a
        fresh window instead of inheriting the stale first-error timestamp."""
        m = ClusterMembership(range(3), suspect_after_ms=30)
        assert not m.suspect(2, "first error")  # window opens
        time.sleep(0.04)  # window would have expired...
        assert not m.mark_alive(2)  # ...but the peer was seen alive
        assert m.epoch == 0
        assert not m.suspect(2, "fresh error")  # fresh window, absorbed again
        assert m.is_alive(2)
        time.sleep(0.04)
        assert m.suspect(2, "persisted past the fresh window")
        assert not m.is_alive(2)

    def test_flapping_storm_bumps_epoch_once_per_real_transition(self):
        """The flapping scenario: a storm of suspicions and liveness flaps
        against one executor.  Debounce absorbs every error inside the
        window; the epoch moves exactly once per REAL transition (one death,
        one rejoin) no matter how many observations piled up, so gossiping
        peers re-applying known facts can never start a re-broadcast storm."""
        m = ClusterMembership(range(4), suspect_after_ms=25)
        for _ in range(20):  # error storm inside one window: all absorbed
            assert not m.suspect(2, "flap")
        assert m.epoch == 0 and m.is_alive(2)
        time.sleep(0.04)
        assert m.suspect(2, "persisted")  # the one real death...
        assert m.epoch == 1
        for _ in range(10):  # ...re-applying it is a no-op (no re-broadcast)
            assert not m.suspect(2, "echo")
            assert not m.mark_dead(2, "echo")
        assert m.epoch == 1
        assert m.mark_alive(2)  # the one real rejoin
        assert m.epoch == 2
        for _ in range(10):
            assert not m.mark_alive(2)
        assert m.epoch == 2 and m.dead() == {}


# ---------------------------------------------------------------------------
# degraded_plan units
# ---------------------------------------------------------------------------


class TestDegradedPlan:
    def test_pow2_shrink(self):
        m, phys, waves = degraded_plan(4, [0, 1, 3])
        assert m == 2 and phys == [0, 1] and waves == 2

    def test_exact_pow2_survivors(self):
        m, phys, waves = degraded_plan(8, [0, 2, 4, 6])
        assert m == 4 and phys == [0, 2, 4, 6] and waves == 2

    def test_single_survivor(self):
        m, phys, waves = degraded_plan(4, [2])
        assert m == 1 and phys == [2] and waves == 4

    def test_wave_count_covers_all_slots(self):
        for n in (2, 4, 8):
            for k in range(1, n + 1):
                m, phys, waves = degraded_plan(n, list(range(k)))
                assert m * waves >= n  # every wave slot is covered
                assert len(phys) == m
                assert m & (m - 1) == 0  # pow2

    def test_no_survivors_raises(self):
        from sparkucx_tpu.core.operation import TransportError

        with pytest.raises(TransportError):
            degraded_plan(4, [])


# ---------------------------------------------------------------------------
# chaos: kill mid-superstep, recover on the shrunk mesh
# ---------------------------------------------------------------------------


def _run_shuffle(cluster, meta, shuffle_id, M, R, seed=7, kill=None, kill_round=1):
    """Stage deterministic blocks, optionally arm a mid-superstep kill, run
    the exchange, and return {(map, reduce): bytes} read from the reducers."""
    rng = np.random.default_rng(seed)
    oracle = {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(shuffle_id, m)
        for r in range(R):
            payload = rng.integers(0, 256, size=2000, dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    try:
        if kill is not None:
            kills = kill if isinstance(kill, (list, tuple)) else [kill]

            def die(**ctx):
                for k in kills:
                    faults.kill_executor(cluster.transport(k))

            faults.arm(
                "exchange.submit", die, times=1, match={"round": kill_round}
            )
        cluster.run_exchange(shuffle_id)
    finally:
        faults.reset()
    blocks = {}
    for (m, r) in oracle:
        consumer = meta.owner_of_reduce(r)
        view, length = cluster.locate_received_block(consumer, shuffle_id, m, r)
        blocks[(m, r)] = bytes(view[:length])
    assert blocks == oracle, "received blocks diverge from staged payloads"
    return blocks


def _mk_cluster(n=4, **conf_kw):
    conf_kw.setdefault("staging_capacity_per_executor", n * 4096)
    conf_kw.setdefault("block_alignment", 128)
    conf_kw.setdefault("elastic", True)
    conf_kw.setdefault("replication_factor", 1)
    conf = TpuShuffleConf(num_executors=n, **conf_kw)
    return TpuShuffleCluster(conf, num_executors=n)


class TestElasticRecovery:
    @pytest.mark.parametrize("impl", ["stock", "pallas"])
    def test_kill_mid_superstep_bit_identical(self, impl):
        """The acceptance scenario: baseline run vs killed-and-recovered run
        must produce byte-identical blocks, for both exchange impls."""
        n, M, R = 4, 12, 8
        base_cluster = _mk_cluster(n, exchange_impl=impl)
        meta = base_cluster.create_shuffle(0, M, R)
        baseline = _run_shuffle(base_cluster, meta, 0, M, R)
        assert base_cluster.elastic_stats["recoveries"] == 0

        cluster = _mk_cluster(n, exchange_impl=impl)
        meta = cluster.create_shuffle(0, M, R)
        recovered = _run_shuffle(cluster, meta, 0, M, R, kill=2)
        assert recovered == baseline
        stats = cluster.elastic_stats
        assert stats["recoveries"] == 1
        assert stats["last_epoch"] == 1
        m, phys = stats["degraded_mesh"]
        assert m == 2 and 2 not in phys
        assert stats["last_recovery_ms"] > 0

    def test_kill_with_memmap_recv_mode(self):
        n, M, R = 4, 12, 8
        base = _run_shuffle(
            (c := _mk_cluster(n, host_recv_mode="memmap")),
            c.create_shuffle(0, M, R), 0, M, R,
        )
        cluster = _mk_cluster(n, host_recv_mode="memmap")
        meta = cluster.create_shuffle(0, M, R)
        assert _run_shuffle(cluster, meta, 0, M, R, kill=3) == base
        assert cluster.elastic_stats["recoveries"] == 1

    def test_factor_zero_raises_typed_no_hang(self):
        cluster = _mk_cluster(4, replication_factor=0)
        meta = cluster.create_shuffle(0, 12, 8)
        with pytest.raises(ExecutorLostError) as ei:
            _run_shuffle(cluster, meta, 0, 12, 8, kill=2)
        assert ei.value.executor_id == 2
        assert "replication.factor=0" in str(ei.value)
        assert "2" in str(ei.value)  # names the lost executor

    def test_elastic_off_raises_typed(self):
        cluster = _mk_cluster(4, elastic=False)
        meta = cluster.create_shuffle(0, 12, 8)
        with pytest.raises(ExecutorLostError) as ei:
            _run_shuffle(cluster, meta, 0, 12, 8, kill=2)
        assert "elastic" in str(ei.value)

    def test_double_failure_primary_and_replica(self):
        """Killing an executor AND its ring successor (the only replica
        holder at factor=1) is unrecoverable: a typed BlockNotFoundError
        names the shuffle and every candidate tried — never a hang."""
        cluster = _mk_cluster(4)
        meta = cluster.create_shuffle(0, 12, 8)
        with pytest.raises(BlockNotFoundError) as ei:
            _run_shuffle(cluster, meta, 0, 12, 8, kill=[1, 2])
        msg = str(ei.value)
        assert "candidates [2]" in msg
        assert "unrecoverable" in msg
        assert ei.value.shuffle_id == 0

    def test_regrow_restores_full_mesh(self):
        """Kill -> shrunk completion -> rejoin -> the NEXT shuffle runs on
        the full mesh again (no recovery, full-epoch exchange)."""
        n, M, R = 4, 12, 8
        cluster = _mk_cluster(n)
        meta = cluster.create_shuffle(0, M, R)
        _run_shuffle(cluster, meta, 0, M, R, kill=2)
        assert cluster.elastic_stats["recoveries"] == 1
        assert cluster.membership.alive() == [0, 1, 3]

        # the executor comes back: fresh store on the same id
        assert cluster.rejoin_executor(2)
        assert cluster.membership.alive() == [0, 1, 2, 3]
        epoch_after_rejoin = cluster.membership.epoch

        meta2 = cluster.create_shuffle(1, M, R)
        blocks = _run_shuffle(cluster, meta2, 1, M, R, seed=11)
        assert len(blocks) == M * R
        # full-mesh run: no new recovery, epoch unchanged
        assert cluster.elastic_stats["recoveries"] == 1
        assert cluster.membership.epoch == epoch_after_rejoin

    def test_quota_engine_fails_fast_on_loss(self):
        """The quota-capped engine has no degraded path: losing an executor
        mid-run must raise the typed error, not hang in a stale plan."""
        cluster = _mk_cluster(4, slot_quota_rows=4)
        meta = cluster.create_shuffle(0, 12, 8)
        with pytest.raises(ExecutorLostError) as ei:
            _run_shuffle(cluster, meta, 0, 12, 8, kill=2)
        assert "quota" in str(ei.value)


# ---------------------------------------------------------------------------
# membership gossip over the peer wire
# ---------------------------------------------------------------------------


class TestMembershipGossip:
    def _wire_cluster(self, n=3, **conf_kw):
        from sparkucx_tpu.transport.peer import PeerTransport

        conf_kw.setdefault("staging_capacity_per_executor", 1 << 20)
        conf = TpuShuffleConf(**conf_kw)
        ts = [PeerTransport(conf, executor_id=i) for i in range(n)]
        addrs = [t.init() for t in ts]
        for t in ts:
            t.membership = ClusterMembership(range(n))
            for j, a in enumerate(addrs):
                if j != t.executor_id:
                    t.add_executor(j, a)
        return ts, addrs

    def test_wire_failure_gossips_suspicion(self):
        from sparkucx_tpu.core.block import MemoryBlock

        ts, _ = self._wire_cluster(3)
        try:
            faults.kill_executor(ts[2])
            buf = MemoryBlock(np.zeros(64, dtype=np.uint8), size=64)
            req = ts[0].fetch_block(2, 1, 0, 0, buf)
            deadline = time.monotonic() + 5
            while not req.completed() and time.monotonic() < deadline:
                ts[0].progress()
                time.sleep(0.002)
            assert req.completed()
            # the observer marked it dead...
            assert not ts[0].membership.is_alive(2)
            # ...and gossiped MEMBER_SUSPECT to the third executor
            deadline = time.monotonic() + 3
            while ts[1].membership.is_alive(2) and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not ts[1].membership.is_alive(2)
            assert "wire failure" in ts[1].membership.dead()[2]
        finally:
            for t in ts:
                t.close()

    def test_rejoin_announcement_restores(self):
        ts, _ = self._wire_cluster(3)
        try:
            for t in ts:
                t.membership.mark_dead(2, "was down")
            ts[2].announce_rejoin()
            assert ts[2].membership.is_alive(2)
            deadline = time.monotonic() + 3
            while (
                not (ts[0].membership.is_alive(2) and ts[1].membership.is_alive(2))
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            assert ts[0].membership.is_alive(2)
            assert ts[1].membership.is_alive(2)
        finally:
            for t in ts:
                t.close()

    def test_rumors_about_self_ignored(self):
        """A live executor is the authority on its own liveness: a gossiped
        suspicion naming the receiver must not kill it locally."""
        from sparkucx_tpu.core.definitions import AmId

        ts, _ = self._wire_cluster(2)
        try:
            ts[1]._on_member_event(int(AmId.MEMBER_SUSPECT), 1, 1, 0)
            assert ts[1].membership.is_alive(1)
        finally:
            for t in ts:
                t.close()


# ---------------------------------------------------------------------------
# SPMD fail-fast guard
# ---------------------------------------------------------------------------


class TestSpmdDegradedGuard:
    def test_degraded_view_fails_before_collective(self):
        from sparkucx_tpu.transport.spmd import SpmdShuffleExecutor

        ex = SpmdShuffleExecutor(TpuShuffleConf())
        try:
            ex.create_shuffle(0, 1, 1)
            ex.membership.mark_dead(0, "chaos")
            with pytest.raises(ExecutorLostError) as ei:
                ex.run_exchange(0)
            assert "SPMD" in str(ei.value)
            assert ei.value.executor_id == 0
        finally:
            ex.close()
