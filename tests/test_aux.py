"""Tests for aux subsystems: task-retry commit semantics, endpoint failure
handling, logging, and stats aggregation (SURVEY.md section 5 parity)."""

import time

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import BytesBlock, MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStats, OperationStatus
from sparkucx_tpu.store.hbm_store import HbmBlockStore
from sparkucx_tpu.transport.peer import PeerTransport
from sparkucx_tpu.utils.stats import StatsAggregator


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


class TestTaskRetryCommit:
    """First-commit-wins (IndexShuffleBlockResolver.scala:161-217 semantics)."""

    def test_retry_after_commit_is_discarded(self):
        store = HbmBlockStore(TpuShuffleConf(staging_capacity_per_executor=1 << 18))
        store.create_shuffle(0, 1, 2)
        w1 = store.map_writer(0, 0)
        w1.write_partition(0, b"first-attempt")
        info1 = w1.commit()

        # speculative/retried task re-runs the same map
        w2 = store.map_writer(0, 0)
        assert w2.is_retry_discard
        w2.write_partition(0, b"second-attempt-different")
        info2 = w2.commit()

        assert info2 == info1  # retry returns the original table
        assert store.read_block(0, 0, 0) == b"first-attempt"
        # no extra space consumed by the discarded attempt
        assert store.stats(0)["bytes_staged"] == len(b"first-attempt")

    def test_uncommitted_rewrite_not_discarded(self):
        # A writer that never committed doesn't poison the map: a second writer
        # (e.g. after task crash before commit) writes normally.
        store = HbmBlockStore(TpuShuffleConf(staging_capacity_per_executor=1 << 18))
        store.create_shuffle(0, 1, 1)
        w1 = store.map_writer(0, 0)
        w1.write_partition(0, b"crashed")
        # no commit — task died
        w2 = store.map_writer(0, 0)
        assert not w2.is_retry_discard
        w2.write_partition(0, b"retried")
        w2.commit()
        assert store.read_block(0, 0, 0) == b"retried"


class TestEndpointFailure:
    def test_dead_server_fails_inflight_requests(self):
        conf = TpuShuffleConf(staging_capacity_per_executor=1 << 18)
        a = PeerTransport(conf, executor_id=1)
        b = PeerTransport(conf, executor_id=2)
        addr_b = b.init()
        a.init()
        a.add_executor(2, addr_b)
        b.register(ShuffleBlockId(0, 0, 0), BytesBlock(b"x"))
        # establish the connection, then kill the server before fetch completes
        a.pre_connect()
        b.close()
        time.sleep(0.1)
        [req] = a.fetch_blocks_by_block_ids(2, [ShuffleBlockId(0, 0, 0)], [_buf(8)], [None])
        deadline = time.monotonic() + 5
        while not req.completed() and time.monotonic() < deadline:
            a.progress()
            time.sleep(0.01)
        res = req.wait(1)
        assert res.status == OperationStatus.FAILURE
        a.close()

    def test_evict_fails_sibling_inflight_batches(self):
        # A send failure evicting the connection must also fail batches already
        # in flight on it — not leave them hanging (code-review regression).
        conf = TpuShuffleConf(staging_capacity_per_executor=1 << 18, max_blocks_per_request=1)
        a = PeerTransport(conf, executor_id=1)
        b = PeerTransport(conf, executor_id=2)
        addr_b = b.init()
        a.init()
        a.add_executor(2, addr_b)
        a.pre_connect()
        conn = a._connection(2)
        # plant a fake in-flight batch riding this connection
        from sparkucx_tpu.core.operation import Request

        req = Request(OperationStats())
        with a._tag_lock:
            a._inflight[999] = ([req], [_buf(8)], [None], conn)
        a._evict(2)
        assert req.completed()
        assert req.wait(1).status == OperationStatus.FAILURE
        a.close()
        b.close()

    def test_send_to_never_started_server_fails_cleanly(self):
        conf = TpuShuffleConf(staging_capacity_per_executor=1 << 18)
        a = PeerTransport(conf, executor_id=1)
        a.init()
        a.add_executor(9, b"127.0.0.1:1")  # nothing listens on port 1
        [req] = a.fetch_blocks_by_block_ids(9, [ShuffleBlockId(0, 0, 0)], [_buf(8)], [None])
        assert req.wait(2).status == OperationStatus.FAILURE
        a.close()


class TestConcurrentWriters:
    def test_parallel_maps_one_region(self):
        # Many map tasks streaming into the same peer region concurrently: the
        # close-time atomic allocation must keep every block intact.
        import threading

        store = HbmBlockStore(TpuShuffleConf(staging_capacity_per_executor=1 << 22))
        store.create_shuffle(0, 16, 1)
        payloads = {m: bytes([m + 1]) * (500 + 37 * m) for m in range(16)}
        errors = []

        def run(m):
            try:
                w = store.map_writer(0, m)
                w.open_partition(0)
                data = payloads[m]
                for i in range(0, len(data), 100):  # streamed in small chunks
                    w.write(data[i : i + 100])
                w.close_partition()
                w.commit()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=run, args=(m,)) for m in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        for m in range(16):
            assert store.read_block(0, m, 0) == payloads[m]


class TestStatsAggregator:
    def test_record_and_summary(self):
        agg = StatsAggregator()
        for size in (100, 200, 300):
            s = OperationStats()
            s.mark_done(recv_size=size)
            agg.record("fetch", s)
        summary = agg.summary("fetch")
        assert summary.ops == 3
        assert summary.bytes == 600
        assert summary.p50_ns is not None
        assert "fetch" in agg.report()

    def test_empty_kind(self):
        agg = StatsAggregator()
        assert agg.summary("nope").ops == 0

    def test_peer_transport_records_fetch_stats(self):
        conf = TpuShuffleConf(staging_capacity_per_executor=1 << 18)
        a = PeerTransport(conf, executor_id=1)
        b = PeerTransport(conf, executor_id=2)
        a.init()
        addr_b = b.init()
        a.add_executor(2, addr_b)
        b.register(ShuffleBlockId(0, 0, 0), BytesBlock(b"stats-me"))
        [req] = a.fetch_blocks_by_block_ids(2, [ShuffleBlockId(0, 0, 0)], [_buf(64)], [None])
        deadline = time.monotonic() + 5
        while not req.completed() and time.monotonic() < deadline:
            a.progress()
            time.sleep(0.001)
        assert req.wait(1).status == OperationStatus.SUCCESS
        assert a.stats_agg.summary("fetch").ops == 1
        assert a.stats_agg.summary("fetch").bytes == 8
        a.close()
        b.close()


class TestLogging:
    def test_get_logger_namespaced(self):
        from sparkucx_tpu.utils.logging import get_logger

        log = get_logger("test.module")
        assert log.name == "sparkucx_tpu.test.module"


class TestAddressCodec:
    """pack/unpack_address — the SerializableDirectBuffer.scala:71-88 twin."""

    def test_roundtrip(self):
        from sparkucx_tpu.utils.serialization import pack_address, unpack_address

        for host, port in [
            ("127.0.0.1", 13337),
            ("::1", 0),                       # IPv6 textual form
            ("worker-0.pod.svc.local", 65535),
            ("bücher.example", 1338),         # non-ASCII utf-8 host
            ("", 42),                         # host-less (port-only) address
        ]:
            blob = pack_address(host, port)
            assert unpack_address(blob) == (host, port)

    def test_wire_layout_is_port_then_utf8_host(self):
        import struct

        from sparkucx_tpu.utils.serialization import pack_address

        blob = pack_address("abc", 258)
        assert struct.unpack_from("<i", blob)[0] == 258
        assert blob[4:] == b"abc"
