"""Tests for the HBM block store (NvkvHandler/NvkvShuffleMapOutputWriter semantics)."""

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.definitions import MapperInfo
from sparkucx_tpu.core.operation import TransportError
from sparkucx_tpu.store.hbm_store import HbmBlockStore, default_peer_ranges

ALIGN = 128


@pytest.fixture
def store():
    s = HbmBlockStore(TpuShuffleConf(staging_capacity_per_executor=1 << 20, block_alignment=ALIGN))
    yield s
    s.close()


class TestPeerRanges:
    def test_balanced(self):
        assert default_peer_ranges(8, 4) == [(0, 2), (2, 4), (4, 6), (6, 8)]

    def test_remainder(self):
        assert default_peer_ranges(10, 4) == [(0, 3), (3, 6), (6, 8), (8, 10)]

    def test_fewer_reducers_than_peers(self):
        ranges = default_peer_ranges(2, 4)
        assert ranges == [(0, 1), (1, 2), (2, 2), (2, 2)]


class TestWriteReadback:
    def test_write_then_read(self, store):
        store.create_shuffle(0, num_mappers=2, num_reducers=4, peer_ranges=default_peer_ranges(4, 2))
        w = store.map_writer(0, 0)
        w.write_partition(0, b"r0-data")
        w.write_partition(2, b"r2-data-xyz")
        w.commit()
        assert store.read_block(0, 0, 0) == b"r0-data"
        assert store.read_block(0, 0, 2) == b"r2-data-xyz"
        assert store.block_length(0, 0, 0) == 7
        assert store.block_length(0, 0, 1) == 0  # never written

    def test_streaming_writes(self, store):
        store.create_shuffle(1, 1, 1)
        w = store.map_writer(1, 0)
        w.open_partition(0)
        for i in range(10):
            w.write(bytes([i]) * 100)
        w.close_partition()
        expected = b"".join(bytes([i]) * 100 for i in range(10))
        assert store.read_block(1, 0, 0) == expected

    def test_sequential_partition_protocol(self, store):
        # NvkvShuffleMapOutputWriter.scala:108 — increasing reduce order enforced.
        store.create_shuffle(2, 1, 4)
        w = store.map_writer(2, 0)
        w.write_partition(2, b"x")
        with pytest.raises(TransportError, match="increasing reduce order"):
            w.open_partition(1)
        with pytest.raises(TransportError, match="no open partition"):
            w.write(b"y")

    def test_double_open_rejected(self, store):
        store.create_shuffle(3, 1, 2)
        w = store.map_writer(3, 0)
        w.open_partition(0)
        with pytest.raises(TransportError, match="still open"):
            w.open_partition(1)

    def test_partition_exceeding_region_rejected(self):
        s = HbmBlockStore(TpuShuffleConf(staging_capacity_per_executor=4096, block_alignment=ALIGN))
        s.create_shuffle(0, 1, 2, peer_ranges=default_peer_ranges(2, 2))
        w = s.map_writer(0, 0)
        w.open_partition(0)
        with pytest.raises(TransportError, match="exceeds a whole region"):
            w.write(b"x" * 4096)

    def test_region_overflow_rolls_over(self):
        # Overflow across partitions spills into a new staging round instead of
        # erroring (multi-round exchange).
        s = HbmBlockStore(TpuShuffleConf(staging_capacity_per_executor=4096, block_alignment=ALIGN))
        s.create_shuffle(1, 2, 2, peer_ranges=default_peer_ranges(2, 2))
        region = s._state(1).region_size
        wa = s.map_writer(1, 0)
        wa.write_partition(0, b"a" * region)
        wa.commit()
        wb = s.map_writer(1, 1)
        wb.write_partition(0, b"c" * 100)  # peer-0 region full -> round 1
        wb.commit()
        assert s.num_rounds(1) == 2
        assert s.read_block(1, 0, 0) == b"a" * region
        assert s.read_block(1, 1, 0) == b"c" * 100
        st = s._state(1)
        assert st.blocks[(0, 0)].round == 0
        assert st.blocks[(1, 0)].round == 1

    def test_empty_partition(self, store):
        store.create_shuffle(4, 1, 2)
        w = store.map_writer(4, 0)
        w.write_partition(0, b"")
        info = w.commit()
        assert info.partitions[0] == (0, 0)
        assert store.read_block(4, 0, 0) == b""


class TestAlignmentAndLayout:
    def test_blocks_aligned(self, store):
        store.create_shuffle(0, 2, 2, peer_ranges=default_peer_ranges(2, 1))
        w0 = store.map_writer(0, 0)
        w0.write_partition(0, b"a" * 100)  # pads to 128
        w0.write_partition(1, b"b" * 200)  # pads to 256
        w1 = store.map_writer(0, 1)
        w1.write_partition(0, b"c" * 50)
        assert store.block_offset(0, 0, 0) == 0
        assert store.block_offset(0, 0, 1) == 128
        assert store.block_offset(0, 1, 0) == 128 + 256
        stats = store.stats(0)
        assert stats["bytes_staged"] == 350
        assert stats["bytes_padded"] == 128 + 256 + 128

    def test_peer_major_regions(self, store):
        # Partitions land in their owning peer's region: this IS the exchange's
        # slot layout — no repacking before the collective.
        store.create_shuffle(0, 1, 4, peer_ranges=default_peer_ranges(4, 2))
        w = store.map_writer(0, 0)
        w.write_partition(0, b"p0")   # peer 0 region
        w.write_partition(2, b"p2")   # peer 1 region
        w.write_partition(3, b"p3")   # peer 1 region
        st = store._state(0)
        assert store.block_offset(0, 0, 0) == 0
        assert store.block_offset(0, 0, 2) == st.region_size
        assert store.block_offset(0, 0, 3) == st.region_size + ALIGN
        assert st.region_used.tolist() == [ALIGN, 2 * ALIGN]

    def test_interleaved_mappers_append_within_region(self, store):
        store.create_shuffle(0, 2, 2, peer_ranges=default_peer_ranges(2, 2))
        w0, w1 = store.map_writer(0, 0), store.map_writer(0, 1)
        w0.write_partition(0, b"m0r0")
        w1.write_partition(0, b"m1r0")
        w0.write_partition(1, b"m0r1")
        assert store.block_offset(0, 0, 0) == 0
        assert store.block_offset(0, 1, 0) == ALIGN
        assert store.read_block(0, 1, 0) == b"m1r0"


class TestCommitAndSeal:
    def test_mapper_info_roundtrip(self, store):
        store.create_shuffle(0, 1, 3)
        w = store.map_writer(0, 0)
        w.write_partition(0, b"abc")
        w.write_partition(2, b"defgh")
        info = w.commit()
        assert info == MapperInfo.unpack(info.pack())
        assert info.partitions[0] == (0, 3)
        assert info.partitions[1] == (0, 0)
        assert info.partitions[2] == (128, 5)

    def test_commit_with_open_partition_rejected(self, store):
        store.create_shuffle(0, 1, 2)
        w = store.map_writer(0, 0)
        w.open_partition(0)
        with pytest.raises(TransportError, match="open partition"):
            w.commit()

    def test_apply_mapper_info(self, store):
        # Peer-process metadata install (the DPU-daemon side of AM id 2).
        store.create_shuffle(0, 2, 2)
        store.apply_mapper_info(MapperInfo(0, 1, ((0, 100), (256, 50))))
        assert store.block_length(0, 1, 0) == 100
        assert store.block_offset(0, 1, 1) == 256
        assert 1 in store.stats(0)["committed_maps"]

    def test_seal_returns_slot_payload_and_sizes(self, store):
        store.create_shuffle(0, 1, 4, peer_ranges=default_peer_ranges(4, 2))
        w = store.map_writer(0, 0)
        w.write_partition(0, b"A" * 100)
        w.write_partition(2, b"B" * 300)
        [(payload, sizes)] = store.seal(0)  # single round
        st = store._state(0)
        assert payload.dtype == np.int32
        assert payload.shape[1] == ALIGN // 4  # one row per alignment unit
        assert sizes.tolist() == [1, 3]  # row counts: 100 B -> 1, 300 B -> 3
        raw = np.asarray(payload).reshape(-1).view(np.uint8)
        assert raw[:100].tobytes() == b"A" * 100
        assert raw[st.region_size : st.region_size + 300].tobytes() == b"B" * 300

    def test_read_after_seal(self, store):
        store.create_shuffle(0, 1, 1)
        w = store.map_writer(0, 0)
        w.write_partition(0, b"persist-me")
        store.seal(0)
        assert store.read_block(0, 0, 0) == b"persist-me"

    def test_no_writes_after_seal(self, store):
        store.create_shuffle(0, 1, 1)
        store.seal(0)
        with pytest.raises(TransportError, match="sealed"):
            store.map_writer(0, 0)

    def test_double_seal_rejected(self, store):
        store.create_shuffle(0, 1, 1)
        store.seal(0)
        with pytest.raises(TransportError, match="sealed"):
            store.seal(0)


class TestLifecycle:
    def test_duplicate_shuffle_rejected(self, store):
        store.create_shuffle(0, 1, 1)
        with pytest.raises(TransportError, match="already exists"):
            store.create_shuffle(0, 1, 1)

    def test_remove_shuffle(self, store):
        store.create_shuffle(0, 1, 1)
        store.remove_shuffle(0)
        with pytest.raises(TransportError, match="unknown shuffle"):
            store.read_block(0, 0, 0)

    def test_unknown_block(self, store):
        store.create_shuffle(0, 1, 1)
        with pytest.raises(TransportError, match="no block"):
            store.read_block(0, 0, 0)

    def test_bad_ids(self, store):
        store.create_shuffle(0, 2, 2)
        with pytest.raises(ValueError):
            store.map_writer(0, 5)
        w = store.map_writer(0, 0)
        with pytest.raises(ValueError):
            w.open_partition(7)

    def test_capacity_too_small(self):
        s = HbmBlockStore(TpuShuffleConf(staging_capacity_per_executor=64))
        with pytest.raises(ValueError, match="too small"):
            s.create_shuffle(0, 1, 8, peer_ranges=default_peer_ranges(8, 8))
