"""Tests for the HBM block store (NvkvHandler/NvkvShuffleMapOutputWriter semantics)."""

import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.definitions import MapperInfo
from sparkucx_tpu.core.operation import TransportError
from sparkucx_tpu.store.hbm_store import HbmBlockStore, default_peer_ranges

ALIGN = 128


@pytest.fixture
def store():
    s = HbmBlockStore(TpuShuffleConf(staging_capacity_per_executor=1 << 20, block_alignment=ALIGN))
    yield s
    s.close()


class TestPeerRanges:
    def test_balanced(self):
        assert default_peer_ranges(8, 4) == [(0, 2), (2, 4), (4, 6), (6, 8)]

    def test_remainder(self):
        assert default_peer_ranges(10, 4) == [(0, 3), (3, 6), (6, 8), (8, 10)]

    def test_fewer_reducers_than_peers(self):
        ranges = default_peer_ranges(2, 4)
        assert ranges == [(0, 1), (1, 2), (2, 2), (2, 2)]


class TestWriteReadback:
    def test_write_then_read(self, store):
        store.create_shuffle(0, num_mappers=2, num_reducers=4, peer_ranges=default_peer_ranges(4, 2))
        w = store.map_writer(0, 0)
        w.write_partition(0, b"r0-data")
        w.write_partition(2, b"r2-data-xyz")
        w.commit()
        assert store.read_block(0, 0, 0) == b"r0-data"
        assert store.read_block(0, 0, 2) == b"r2-data-xyz"
        assert store.block_length(0, 0, 0) == 7
        assert store.block_length(0, 0, 1) == 0  # never written

    def test_streaming_writes(self, store):
        store.create_shuffle(1, 1, 1)
        w = store.map_writer(1, 0)
        w.open_partition(0)
        for i in range(10):
            w.write(bytes([i]) * 100)
        w.close_partition()
        expected = b"".join(bytes([i]) * 100 for i in range(10))
        assert store.read_block(1, 0, 0) == expected

    def test_sequential_partition_protocol(self, store):
        # NvkvShuffleMapOutputWriter.scala:108 — increasing reduce order enforced.
        store.create_shuffle(2, 1, 4)
        w = store.map_writer(2, 0)
        w.write_partition(2, b"x")
        with pytest.raises(TransportError, match="increasing reduce order"):
            w.open_partition(1)
        with pytest.raises(TransportError, match="no open partition"):
            w.write(b"y")

    def test_double_open_rejected(self, store):
        store.create_shuffle(3, 1, 2)
        w = store.map_writer(3, 0)
        w.open_partition(0)
        with pytest.raises(TransportError, match="still open"):
            w.open_partition(1)

    def test_partition_exceeding_region_rejected(self):
        s = HbmBlockStore(TpuShuffleConf(staging_capacity_per_executor=4096, block_alignment=ALIGN))
        s.create_shuffle(0, 1, 2, peer_ranges=default_peer_ranges(2, 2))
        w = s.map_writer(0, 0)
        w.open_partition(0)
        with pytest.raises(TransportError, match="exceeds a whole region"):
            w.write(b"x" * 4096)

    def test_region_overflow_rolls_over(self):
        # Overflow across partitions spills into a new staging round instead of
        # erroring (multi-round exchange).
        s = HbmBlockStore(TpuShuffleConf(staging_capacity_per_executor=4096, block_alignment=ALIGN))
        s.create_shuffle(1, 2, 2, peer_ranges=default_peer_ranges(2, 2))
        region = s._state(1).region_size
        wa = s.map_writer(1, 0)
        wa.write_partition(0, b"a" * region)
        wa.commit()
        wb = s.map_writer(1, 1)
        wb.write_partition(0, b"c" * 100)  # peer-0 region full -> round 1
        wb.commit()
        assert s.num_rounds(1) == 2
        assert s.read_block(1, 0, 0) == b"a" * region
        assert s.read_block(1, 1, 0) == b"c" * 100
        st = s._state(1)
        assert st.blocks[(0, 0)].round == 0
        assert st.blocks[(1, 0)].round == 1

    def test_empty_partition(self, store):
        store.create_shuffle(4, 1, 2)
        w = store.map_writer(4, 0)
        w.write_partition(0, b"")
        info = w.commit()
        assert info.partitions[0] == (0, 0)
        assert store.read_block(4, 0, 0) == b""


class TestDiskSpillTier:
    """Completed staging rounds move to np.memmap files (the capacity-beyond-RAM
    role of the reference's DPU-attached NVMe, NvkvHandler.scala:160-242), so a
    shuffle larger than the staging RAM budget streams through bounded memory."""

    def _fill_rounds(self, s, shuffle_id, num_rounds, region):
        """Write num_rounds full regions for reducer 0 via distinct mappers;
        returns the oracle {(map_id, 0): payload}."""
        oracle = {}
        for m in range(num_rounds):
            payload = bytes([m + 1]) * region
            w = s.map_writer(shuffle_id, m)
            w.write_partition(0, payload)
            w.commit()
            oracle[(m, 0)] = payload
        return oracle

    def test_rounds_spill_to_memmap_and_read_back(self, tmp_path):
        import os

        s = HbmBlockStore(
            TpuShuffleConf(
                staging_capacity_per_executor=4096,
                block_alignment=ALIGN,
                spill_dir=str(tmp_path),
            )
        )
        # 8 rounds x 4096 B through a 4096 B RAM budget: 8x larger than staging
        s.create_shuffle(0, 8, 1)
        region = s._state(0).region_size
        oracle = self._fill_rounds(s, 0, 8, region)
        assert s.num_rounds(0) == 8
        st = s._state(0)
        assert len(st.prev_rounds) == 7
        assert all(isinstance(p, np.memmap) for p, _ in st.prev_rounds)
        spilled = [f for f in os.listdir(str(tmp_path)) if not f.startswith(".")]
        assert len(spilled) == 1  # the per-store spill subdir
        files = os.listdir(tmp_path / spilled[0])
        assert len(files) == 7
        for (m, r), expect in oracle.items():
            assert s.read_block(0, m, r) == expect, f"round {m} corrupted"
        # zero-copy serving handle works against the memmap too
        arr, off, ln = s.block_staging_view(0, 0, 0)
        assert bytes(arr[off : off + ln]) == oracle[(0, 0)]
        s.remove_shuffle(0)
        assert os.listdir(str(tmp_path)) == []  # files AND subdir reclaimed
        s.close()

    def test_seal_serves_spilled_rounds(self, tmp_path):
        s = HbmBlockStore(
            TpuShuffleConf(
                staging_capacity_per_executor=4096,
                block_alignment=ALIGN,
                spill_dir=str(tmp_path),
            )
        )
        s.create_shuffle(0, 3, 1)
        region = s._state(0).region_size
        oracle = self._fill_rounds(s, 0, 3, region)
        rounds = s.seal(0)
        assert len(rounds) == 3
        for m, (payload, sizes) in enumerate(rounds):
            flat = np.asarray(payload).reshape(-1).view(np.uint8)
            assert flat[:region].tobytes() == oracle[(m, 0)]
            assert int(sizes[0]) == region // ALIGN
        s.close()

    def test_spill_disabled_keeps_ram_snapshots(self, tmp_path):
        s = HbmBlockStore(
            TpuShuffleConf(
                staging_capacity_per_executor=4096,
                block_alignment=ALIGN,
                spill_to_disk=False,
                spill_dir=str(tmp_path),
            )
        )
        s.create_shuffle(0, 2, 1)
        region = s._state(0).region_size
        oracle = self._fill_rounds(s, 0, 2, region)
        st = s._state(0)
        assert len(st.prev_rounds) == 1
        assert not isinstance(st.prev_rounds[0][0], np.memmap)
        import os

        assert os.listdir(str(tmp_path)) == []
        assert s.read_block(0, 0, 0) == oracle[(0, 0)]
        s.close()

    def test_spill_cap_enforced(self, tmp_path):
        s = HbmBlockStore(
            TpuShuffleConf(
                staging_capacity_per_executor=4096,
                block_alignment=ALIGN,
                spill_dir=str(tmp_path),
                spill_disk_cap_bytes=2 * 4096,
            )
        )
        s.create_shuffle(0, 4, 1)
        region = s._state(0).region_size
        self._fill_rounds(s, 0, 3, region)  # two rounds spilled = cap
        with pytest.raises(TransportError, match="spill cap"):
            w = s.map_writer(0, 3)
            w.write_partition(0, b"x" * region)
        s.close()

    def test_shuffle_beyond_ram_budget_end_to_end(self, tmp_path):
        """BASELINE-shaped gate: exchange a shuffle ~10x the configured staging
        RAM budget through multi-round collectives and verify every block
        against the oracle (VERDICT round-1 item 4's done criterion,
        scaled down via the small capacity)."""
        from sparkucx_tpu.transport.tpu import TpuShuffleCluster

        n, M, R = 2, 6, 4
        conf = TpuShuffleConf(
            staging_capacity_per_executor=8192,
            block_alignment=ALIGN,
            num_executors=n,
            spill_dir=str(tmp_path),
        )
        cluster = TpuShuffleCluster(conf, num_executors=n)
        meta = cluster.create_shuffle(0, M, R)
        rng = np.random.default_rng(42)
        region = cluster.transport(0).store._state(0).region_size
        oracle = {}
        for m in range(M):
            t = cluster.transport(meta.map_owner[m])
            w = t.store.map_writer(0, m)
            for r in range(R):
                # ~0.9 region per block forces a rollover nearly every write
                payload = rng.integers(
                    0, 256, size=int(region * 0.9), dtype=np.uint8
                ).tobytes()
                oracle[(m, r)] = payload
                w.write_partition(r, payload)
            t.commit_block(w.commit().pack())
        total = sum(len(v) for v in oracle.values())
        assert total > 10 * conf.staging_capacity_per_executor
        cluster.run_exchange(0)
        for (m, r), expect in oracle.items():
            consumer = meta.owner_of_reduce(r)
            view, ln = cluster.locate_received_block(consumer, 0, m, r)
            assert ln == len(expect)
            assert view[:ln].tobytes() == expect, f"mismatch at ({m},{r})"
        cluster.remove_shuffle(0)
        import os

        leftovers = [
            f for d in os.listdir(str(tmp_path)) for f in os.listdir(tmp_path / d)
        ]
        assert leftovers == []


class TestAlignmentAndLayout:
    def test_blocks_aligned(self, store):
        store.create_shuffle(0, 2, 2, peer_ranges=default_peer_ranges(2, 1))
        w0 = store.map_writer(0, 0)
        w0.write_partition(0, b"a" * 100)  # pads to 128
        w0.write_partition(1, b"b" * 200)  # pads to 256
        w1 = store.map_writer(0, 1)
        w1.write_partition(0, b"c" * 50)
        assert store.block_offset(0, 0, 0) == 0
        assert store.block_offset(0, 0, 1) == 128
        assert store.block_offset(0, 1, 0) == 128 + 256
        stats = store.stats(0)
        assert stats["bytes_staged"] == 350
        assert stats["bytes_padded"] == 128 + 256 + 128

    def test_peer_major_regions(self, store):
        # Partitions land in their owning peer's region: this IS the exchange's
        # slot layout — no repacking before the collective.
        store.create_shuffle(0, 1, 4, peer_ranges=default_peer_ranges(4, 2))
        w = store.map_writer(0, 0)
        w.write_partition(0, b"p0")   # peer 0 region
        w.write_partition(2, b"p2")   # peer 1 region
        w.write_partition(3, b"p3")   # peer 1 region
        st = store._state(0)
        assert store.block_offset(0, 0, 0) == 0
        assert store.block_offset(0, 0, 2) == st.region_size
        assert store.block_offset(0, 0, 3) == st.region_size + ALIGN
        assert st.region_used.tolist() == [ALIGN, 2 * ALIGN]

    def test_interleaved_mappers_append_within_region(self, store):
        store.create_shuffle(0, 2, 2, peer_ranges=default_peer_ranges(2, 2))
        w0, w1 = store.map_writer(0, 0), store.map_writer(0, 1)
        w0.write_partition(0, b"m0r0")
        w1.write_partition(0, b"m1r0")
        w0.write_partition(1, b"m0r1")
        assert store.block_offset(0, 0, 0) == 0
        assert store.block_offset(0, 1, 0) == ALIGN
        assert store.read_block(0, 1, 0) == b"m1r0"


class TestCommitAndSeal:
    def test_mapper_info_roundtrip(self, store):
        store.create_shuffle(0, 1, 3)
        w = store.map_writer(0, 0)
        w.write_partition(0, b"abc")
        w.write_partition(2, b"defgh")
        info = w.commit()
        assert info == MapperInfo.unpack(info.pack())
        assert info.partitions[0] == (0, 3)
        assert info.partitions[1] == (0, 0)
        assert info.partitions[2] == (128, 5)

    def test_commit_with_open_partition_rejected(self, store):
        store.create_shuffle(0, 1, 2)
        w = store.map_writer(0, 0)
        w.open_partition(0)
        with pytest.raises(TransportError, match="open partition"):
            w.commit()

    def test_apply_mapper_info(self, store):
        # Peer-process metadata install (the DPU-daemon side of AM id 2).
        store.create_shuffle(0, 2, 2)
        store.apply_mapper_info(MapperInfo(0, 1, ((0, 100), (256, 50))))
        assert store.block_length(0, 1, 0) == 100
        assert store.block_offset(0, 1, 1) == 256
        assert 1 in store.stats(0)["committed_maps"]

    def test_seal_returns_slot_payload_and_sizes(self, store):
        store.create_shuffle(0, 1, 4, peer_ranges=default_peer_ranges(4, 2))
        w = store.map_writer(0, 0)
        w.write_partition(0, b"A" * 100)
        w.write_partition(2, b"B" * 300)
        [(payload, sizes)] = store.seal(0)  # single round
        st = store._state(0)
        assert payload.dtype == np.int32
        assert payload.shape[1] == ALIGN // 4  # one row per alignment unit
        assert sizes.tolist() == [1, 3]  # row counts: 100 B -> 1, 300 B -> 3
        raw = np.asarray(payload).reshape(-1).view(np.uint8)
        assert raw[:100].tobytes() == b"A" * 100
        assert raw[st.region_size : st.region_size + 300].tobytes() == b"B" * 300

    def test_read_after_seal(self, store):
        store.create_shuffle(0, 1, 1)
        w = store.map_writer(0, 0)
        w.write_partition(0, b"persist-me")
        store.seal(0)
        assert store.read_block(0, 0, 0) == b"persist-me"

    def test_no_writes_after_seal(self, store):
        store.create_shuffle(0, 1, 1)
        store.seal(0)
        with pytest.raises(TransportError, match="sealed"):
            store.map_writer(0, 0)

    def test_double_seal_rejected(self, store):
        store.create_shuffle(0, 1, 1)
        store.seal(0)
        with pytest.raises(TransportError, match="sealed"):
            store.seal(0)


class TestLifecycle:
    def test_duplicate_shuffle_rejected(self, store):
        store.create_shuffle(0, 1, 1)
        with pytest.raises(TransportError, match="already exists"):
            store.create_shuffle(0, 1, 1)

    def test_remove_shuffle(self, store):
        store.create_shuffle(0, 1, 1)
        store.remove_shuffle(0)
        with pytest.raises(TransportError, match="unknown shuffle"):
            store.read_block(0, 0, 0)

    def test_unknown_block(self, store):
        store.create_shuffle(0, 1, 1)
        with pytest.raises(TransportError, match="no block"):
            store.read_block(0, 0, 0)

    def test_bad_ids(self, store):
        store.create_shuffle(0, 2, 2)
        with pytest.raises(ValueError):
            store.map_writer(0, 5)
        w = store.map_writer(0, 0)
        with pytest.raises(ValueError):
            w.open_partition(7)

    def test_capacity_too_small(self):
        s = HbmBlockStore(TpuShuffleConf(staging_capacity_per_executor=64))
        with pytest.raises(ValueError, match="too small"):
            s.create_shuffle(0, 1, 8, peer_ranges=default_peer_ranges(8, 8))


class TestSpillDirLifecycle:
    """The DEFAULT spill location (spill_dir=None -> per-store system tempdir,
    prefix sparkucx_tpu_spill_e*) must be fully reclaimed: per-shuffle files on
    remove_shuffle, the directory itself on close() or when the last spilled
    shuffle goes away.  Guards the leak where long-lived executors littered
    /tmp with sparkucx_tpu_spill_e* dirs."""

    def _fill_rounds(self, s, shuffle_id, num_rounds, region):
        for m in range(num_rounds):
            w = s.map_writer(shuffle_id, m)
            w.write_partition(0, bytes([m + 1]) * region)
            w.commit()

    def _spilled_store(self):
        s = HbmBlockStore(
            TpuShuffleConf(staging_capacity_per_executor=4096, block_alignment=ALIGN)
        )
        s.create_shuffle(0, 3, 1)
        self._fill_rounds(s, 0, 3, s._state(0).region_size)
        return s

    def test_close_removes_default_tempdir(self):
        import os

        s = self._spilled_store()
        d = s._spill_dir
        assert d is not None and os.path.isdir(d)
        assert os.path.basename(d).startswith("sparkucx_tpu_spill_e")
        s.close()
        assert not os.path.exists(d)

    def test_remove_last_spilled_shuffle_reclaims_dir(self):
        import os

        s = self._spilled_store()
        d = s._spill_dir
        assert d is not None and len(os.listdir(d)) == 2  # 3 rounds, 2 spilled
        s.remove_shuffle(0)
        # files AND the tempdir itself are gone; bookkeeping reset
        assert not os.path.exists(d)
        assert s._spill_dir is None
        # a later spill transparently recreates a fresh dir
        s.create_shuffle(1, 3, 1)
        self._fill_rounds(s, 1, 3, s._state(1).region_size)
        d2 = s._spill_dir
        assert d2 is not None and d2 != d and os.path.isdir(d2)
        s.close()
        assert not os.path.exists(d2)

    def test_no_leftover_spill_dirs_in_tempdir(self):
        import os
        import tempfile

        def leftovers():
            return {
                f
                for f in os.listdir(tempfile.gettempdir())
                if f.startswith("sparkucx_tpu_spill_e")
            }

        before = leftovers()
        s = self._spilled_store()
        s.close()
        assert leftovers() == before
