"""Transitive closure (ops/tc.py) vs the CPU oracle — the SparkTC gate of the
reference's integration harness (buildlib/test.sh:175-179), on the CPU mesh."""

import numpy as np
import pytest

from sparkucx_tpu.ops.exchange import make_mesh
from sparkucx_tpu.ops.tc import TcSpec, oracle_tc, run_transitive_closure

N_EXEC = 4


def _spec(edge_cap=256, tc_cap=2048, join_cap=4096, **kw):
    return TcSpec(
        num_executors=N_EXEC,
        edge_capacity=edge_cap,
        tc_capacity=tc_cap,
        join_capacity=join_cap,
        **kw,
    )


def _random_graph(rng, vertices, edges):
    return rng.integers(0, vertices, size=(edges, 2), dtype=np.uint32)


class TestTransitiveClosure:
    def test_chain_graph(self):
        # 0->1->2->...->9: closure is all (i, j), i<j — 45 pairs, 9 rounds max
        edges = np.array([(i, i + 1) for i in range(9)], np.uint32)
        mesh = make_mesh(N_EXEC)
        got, rounds = run_transitive_closure(mesh, _spec(), edges)
        want = oracle_tc(edges)
        assert np.array_equal(got, want)
        assert len(got) == 45

    def test_cycle_graph(self):
        # 0->1->2->3->0: closure is the complete digraph on 4 vertices (16 pairs)
        edges = np.array([(0, 1), (1, 2), (2, 3), (3, 0)], np.uint32)
        mesh = make_mesh(N_EXEC)
        got, _ = run_transitive_closure(mesh, _spec(), edges)
        assert np.array_equal(got, oracle_tc(edges))
        assert len(got) == 16

    @pytest.mark.parametrize("seed", [0, 1])
    def test_random_graph_vs_oracle(self, seed):
        # the SparkTC shape: random edges over a small vertex set (dense closure)
        rng = np.random.default_rng(seed)
        edges = _random_graph(rng, vertices=24, edges=60)
        mesh = make_mesh(N_EXEC)
        got, rounds = run_transitive_closure(mesh, _spec(), edges)
        want = oracle_tc(edges)
        assert np.array_equal(got, want), (
            f"closure mismatch: got {len(got)} pairs, want {len(want)}"
        )

    def test_already_closed(self):
        # closure of a closure converges in one round with no growth
        edges = oracle_tc(np.array([(0, 1), (1, 2)], np.uint32))
        mesh = make_mesh(N_EXEC)
        got, rounds = run_transitive_closure(mesh, _spec(), edges)
        assert np.array_equal(got, edges)
        assert rounds == 1

    def test_duplicate_edges_and_self_loops(self):
        edges = np.array([(0, 1), (0, 1), (1, 1), (1, 2)], np.uint32)
        mesh = make_mesh(N_EXEC)
        got, _ = run_transitive_closure(mesh, _spec(), edges)
        assert np.array_equal(got, oracle_tc(edges))

    def test_capacity_overflow_raises(self):
        # closure of a 12-chain is 66 pairs; tc_capacity 4/shard (16 global)
        # cannot hold it — the overflow must surface, not silently truncate
        edges = np.array([(i, i + 1) for i in range(11)], np.uint32)
        mesh = make_mesh(N_EXEC)
        with pytest.raises(RuntimeError, match="overflow"):
            run_transitive_closure(mesh, _spec(tc_cap=4, join_cap=8), edges)

    def test_non_convergence_raises(self):
        # diameter 19 > max_rounds 5: a partial closure must never be returned
        edges = np.array([(i, i + 1) for i in range(19)], np.uint32)
        mesh = make_mesh(N_EXEC)
        with pytest.raises(RuntimeError, match="no fixpoint"):
            run_transitive_closure(mesh, _spec(), edges, max_rounds=5)

    def test_vertex_id_range_guard(self):
        edges = np.array([(0, 0xFFFFFFFF)], np.uint32)
        mesh = make_mesh(N_EXEC)
        with pytest.raises(ValueError, match="vertex ids"):
            run_transitive_closure(mesh, _spec(), edges)
