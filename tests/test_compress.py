"""Tests for the payload-reduction layer (compress tier a + quantize tier b).

Tier (a) — lossless page codecs (utils/pagecodec.py) and the wire policy
(ops/compress.py CompressSpec/encode_chunk): every codec round-trips bit-exact
on the shapes the data plane moves, adversarial payloads raise CodecError and
never over-read, unprofitable pages fall back to raw, and the server's
encoded-chunk pool serves steady-state fetches without re-encoding.

Tier (b) — lossy opt-in block quantization (QuantizeSpec, the quantized
exchange builders, and the groupby partial-aggregate wiring): dequantized
results stay inside the documented ``error_bound``, keys/counts stay exact,
fused == unfused, and every misuse (mode off, integer dtypes, non-partial
plans) is rejected at validate time.
"""

import struct

import jax
import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import BytesBlock, MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus
from sparkucx_tpu.ops.compress import (
    CompressSpec,
    QuantizeSpec,
    dequantize_rows,
    encode_chunk,
    quantize_rows,
)
from sparkucx_tpu.utils.pagecodec import (
    CODEC_DELTA,
    CODEC_DICT,
    CODEC_RAW,
    CODEC_RLE,
    CodecError,
    decode_page,
    encode_page,
)

_ALL_CODECS = (CODEC_DICT, CODEC_RLE, CODEC_DELTA)


def _roundtrip(codec_id, page):
    enc = encode_page(codec_id, page)
    if enc is None:
        return None
    assert len(enc) < len(page), "encoder returned a non-shrinking encoding"
    out = bytearray(len(page))
    decode_page(codec_id, enc, out)
    assert bytes(out) == page, "codec round-trip diverged"
    return enc


def _pages():
    """The case matrix: every shape the codecs are tuned for plus the ones
    they must decline (noise), with word tails and degenerate sizes."""
    rng = np.random.default_rng(7)
    nwords = 4096
    alpha = np.unique(rng.integers(0, 2**32, size=97, dtype=np.uint64).astype("<u4"))
    wide = np.unique(rng.integers(0, 2**31, size=600, dtype=np.uint64).astype("<u4"))
    huge = np.unique(rng.integers(0, 2**31, size=3000, dtype=np.uint64).astype("<u4"))
    seq_base = np.uint32(2**31)
    near = (
        seq_base
        + np.cumsum(rng.integers(-100, 100, size=nwords), dtype=np.int64).astype(
            np.uint32
        )
    ).astype("<u4")
    wrap = (
        (np.arange(nwords, dtype=np.uint64) * 3 + 2**32 - 100) % 2**32
    ).astype("<u4")
    zeros = bytes(4 * nwords)
    return {
        "dict_small": alpha[rng.integers(0, alpha.size, nwords)].tobytes(),
        "dict_wide_hash": wide[rng.integers(0, wide.size, 4 * nwords)].tobytes(),
        "dict_u16_search": huge[rng.integers(0, huge.size, 16 * nwords)].tobytes(),
        "clustered": np.repeat(
            alpha[:64], nwords // 64
        ).astype("<u4").tobytes(),
        "zeros": zeros,
        "sorted": np.sort(
            rng.integers(0, 2**28, size=nwords, dtype=np.uint64).astype("<u4")
        ).tobytes(),
        "near_seq": near.tobytes(),
        "wrap_delta": wrap.tobytes(),
        "noise": rng.integers(0, 256, size=4 * nwords, dtype=np.uint8).tobytes(),
        "tail1": zeros + b"\x01",
        "tail2": zeros + b"\x01\x02",
        "tail3": zeros + b"\x01\x02\x03",
        "one_word": b"\xde\xad\xbe\xef",
        "tail_only": b"\x01\x02\x03",
    }


class TestPageCodecRoundtrip:
    @pytest.mark.parametrize("codec_id", _ALL_CODECS)
    def test_case_matrix_roundtrips(self, codec_id):
        for name, page in _pages().items():
            _roundtrip(codec_id, page)  # asserts equality whenever it encodes

    def test_expected_pages_actually_compress(self):
        pages = _pages()
        # each codec must land its headline shape (ratio checked, not assumed)
        assert len(_roundtrip(CODEC_DICT, pages["dict_small"])) < len(pages["dict_small"]) // 3
        assert _roundtrip(CODEC_DICT, pages["dict_wide_hash"]) is not None
        assert _roundtrip(CODEC_DICT, pages["dict_u16_search"]) is not None
        assert len(_roundtrip(CODEC_RLE, pages["clustered"])) < len(pages["clustered"]) // 20
        assert len(_roundtrip(CODEC_RLE, pages["zeros"])) < 32
        assert _roundtrip(CODEC_DELTA, pages["sorted"]) is not None
        assert len(_roundtrip(CODEC_DELTA, pages["near_seq"])) < len(pages["near_seq"]) // 3
        assert _roundtrip(CODEC_DELTA, pages["wrap_delta"]) is not None

    @pytest.mark.parametrize("codec_id", _ALL_CODECS)
    def test_noise_and_degenerates_fall_back(self, codec_id):
        pages = _pages()
        for name in ("noise", "one_word", "tail_only"):
            assert encode_page(codec_id, pages[name]) is None, name
        assert encode_page(codec_id, b"") is None

    @pytest.mark.parametrize("codec_id", _ALL_CODECS)
    def test_word_tails_survive(self, codec_id):
        for name in ("tail1", "tail2", "tail3"):
            _roundtrip(codec_id, _pages()[name])

    @pytest.mark.parametrize("codec_id", _ALL_CODECS)
    def test_random_fuzz_roundtrips(self, codec_id, rng):
        for _ in range(30):
            n = int(rng.integers(0, 2000))
            kind = rng.integers(0, 3)
            if kind == 0:  # low-cardinality words + tail
                vals = rng.integers(0, 9, size=(n + 3) // 4, dtype=np.uint64)
                page = vals.astype("<u4").tobytes()[:n]
            elif kind == 1:  # runs
                page = (b"\x07\x00\x00\x00" * ((n + 3) // 4))[:n]
            else:  # raw noise
                page = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
            _roundtrip(codec_id, page)

    def test_raw_codec_copies_exactly(self):
        page = b"raw-page-payload" * 9
        out = bytearray(len(page))
        decode_page(CODEC_RAW, page, out)
        assert bytes(out) == page
        assert encode_page(CODEC_RAW, page) is None


class TestCodecAdversarial:
    """Corrupt/hostile payloads must raise CodecError (a ValueError) — never
    over-read, scatter out of bounds, or leak a different exception type."""

    @pytest.mark.parametrize("codec_id", _ALL_CODECS)
    def test_mutations_never_crash_or_overread(self, codec_id, rng):
        pages = _pages()
        source = {
            CODEC_DICT: pages["dict_small"],
            CODEC_RLE: pages["clustered"],
            CODEC_DELTA: pages["near_seq"],
        }[codec_id]
        enc = encode_page(codec_id, source)
        # length mutations break the internal size accounting: ALWAYS caught
        for bad in (enc[: len(enc) // 2], enc[:-1], enc + b"\x00", enc + enc, b""):
            with pytest.raises(CodecError):
                decode_page(codec_id, bad, bytearray(len(source)))
        # garbled interiors may decode to wrong-but-in-range bytes (integrity
        # is the crc's job, not the codec's) — but they may ONLY raise
        # CodecError, never over-read, scatter out of range, or crash
        for _ in range(60):
            buf = bytearray(enc)
            for _ in range(int(rng.integers(1, 4))):
                buf[int(rng.integers(0, len(buf)))] ^= int(rng.integers(1, 256))
            try:
                decode_page(codec_id, bytes(buf), bytearray(len(source)))
            except CodecError:
                pass

    def test_rle_length_sum_mismatch(self):
        # 2 runs of 1 word each claiming a 3-word destination
        enc = (
            struct.pack("<I", 2)
            + np.array([1, 1], "<u4").tobytes()
            + np.array([7, 9], "<u4").tobytes()
        )
        with pytest.raises(CodecError, match="expand"):
            decode_page(CODEC_RLE, enc, bytearray(12))

    def test_rle_claimed_runs_exceed_payload(self):
        with pytest.raises(CodecError, match="payload"):
            decode_page(CODEC_RLE, struct.pack("<I", 2**30), bytearray(64))

    def test_dict_index_out_of_range(self):
        # 1 word, 1 dictionary entry, width 1 — but the index byte says 5
        enc = struct.pack("<IIB", 1, 1, 1) + struct.pack("<I", 42) + b"\x05"
        with pytest.raises(CodecError, match="range"):
            decode_page(CODEC_DICT, enc, bytearray(4))

    def test_dict_invalid_width(self):
        enc = struct.pack("<IIB", 1, 1, 3) + struct.pack("<I", 42) + b"\x00"
        with pytest.raises(CodecError, match="width"):
            decode_page(CODEC_DICT, enc, bytearray(4))

    def test_dict_empty_dictionary_with_words(self):
        with pytest.raises(CodecError):
            decode_page(CODEC_DICT, struct.pack("<IIB", 1, 0, 1) + b"\x00", bytearray(4))

    def test_dict_word_count_disagrees_with_destination(self):
        enc = struct.pack("<IIB", 9, 1, 1) + struct.pack("<I", 42) + b"\x00" * 9
        with pytest.raises(CodecError, match="destination|claims"):
            decode_page(CODEC_DICT, enc, bytearray(4))

    @pytest.mark.parametrize("nbytes", [0, 4, 255])
    def test_delta_invalid_width(self, nbytes):
        enc = struct.pack("<IIB", 2, 0, nbytes) + b"\x00" * 8
        with pytest.raises(CodecError, match="width"):
            decode_page(CODEC_DELTA, enc, bytearray(8))

    def test_delta_zero_words(self):
        with pytest.raises(CodecError, match="zero"):
            decode_page(CODEC_DELTA, struct.pack("<IIB", 0, 0, 1), bytearray(8))

    def test_delta_payload_length_mismatch(self):
        enc = struct.pack("<IIB", 4, 0, 2) + b"\x00" * 3  # needs 6 delta bytes
        with pytest.raises(CodecError, match="payload"):
            decode_page(CODEC_DELTA, enc, bytearray(16))

    def test_raw_size_mismatch(self):
        with pytest.raises(CodecError, match="raw"):
            decode_page(CODEC_RAW, b"abc", bytearray(4))

    def test_unknown_codec_id(self):
        with pytest.raises(CodecError, match="unknown"):
            decode_page(99, b"abc", bytearray(3))
        with pytest.raises(ValueError, match="unknown"):
            encode_page(99, b"abcd")

    def test_codec_error_is_value_error(self):
        assert issubclass(CodecError, ValueError)


class TestEncodeChunk:
    def test_off_spec_never_encodes(self):
        cid, enc = encode_chunk(CompressSpec(), bytes(1 << 16))
        assert (cid, enc) == (CODEC_RAW, None)

    def test_min_chunk_gate(self):
        spec = CompressSpec(codec="rle", min_chunk_bytes=4096)
        assert encode_chunk(spec, bytes(4095)) == (CODEC_RAW, None)
        cid, enc = encode_chunk(spec, bytes(4096))
        assert cid == CODEC_RLE and enc is not None and len(enc) < 4096

    def test_incompressible_falls_back_raw(self):
        spec = CompressSpec(codec="dict", min_chunk_bytes=0)
        noise = np.random.default_rng(3).integers(0, 256, 8192, np.uint8).tobytes()
        assert encode_chunk(spec, noise) == (CODEC_RAW, None)

    def test_from_conf_and_validation(self):
        conf = TpuShuffleConf(wire_compress_codec="delta", compress_min_chunk_bytes=1024)
        spec = CompressSpec.from_conf(conf)
        assert spec.codec == "delta" and spec.min_chunk_bytes == 1024
        assert spec.enabled and spec.codec_id == CODEC_DELTA
        assert not CompressSpec().enabled
        with pytest.raises(ValueError, match="codec"):
            CompressSpec(codec="zstd").validate()
        with pytest.raises(ValueError, match="min_chunk_bytes"):
            CompressSpec(codec="rle", min_chunk_bytes=-1).validate()
        with pytest.raises(ValueError, match="wire_compress_codec"):
            TpuShuffleConf(wire_compress_codec="zstd").validate()


# ----------------------------------------------------------------------
# serve-side encoded-chunk pool (transport/peer.py)
# ----------------------------------------------------------------------


def _pair(**kw):
    from sparkucx_tpu.transport.peer import PeerTransport

    conf = TpuShuffleConf(**kw)
    a = PeerTransport(conf, executor_id=1)
    b = PeerTransport(conf, executor_id=2)
    a.init()
    a.add_executor(2, b.init())
    return a, b


def _fetch(a, bids, sizes, timeout=10.0):
    import time

    bufs = [MemoryBlock(np.zeros(n, np.uint8), size=n) for n in sizes]
    reqs = a.fetch_blocks_by_block_ids(2, bids, bufs, [None] * len(bids))
    deadline = time.monotonic() + timeout
    while not all(r.completed() for r in reqs):
        a.progress()
        if time.monotonic() > deadline:
            raise TimeoutError("fetch did not complete")
        time.sleep(0.001)
    for r in reqs:
        assert r.wait(0).status == OperationStatus.SUCCESS, str(r.wait(0).error)
    return [bytes(buf.host_view()) for buf in bufs]


class TestEncodedPool:
    def test_refetch_hits_the_pool(self):
        a, b = _pair(wire_compress_codec="rle")
        try:
            bid = ShuffleBlockId(0, 0, 0)
            payload = bytes(64 << 10)  # zeros: maximal rle page
            b.register(bid, BytesBlock(payload))
            assert _fetch(a, [bid], [len(payload)]) == [payload]
            snap1 = b.server.compress_snapshot()
            assert snap1["encoded_chunks"] >= 1
            assert snap1["wire_bytes"] < snap1["raw_bytes"]
            assert _fetch(a, [bid], [len(payload)]) == [payload]
            snap2 = b.server.compress_snapshot()
            # sealed blocks are immutable: the refetch served cached encodings
            assert snap2["cache_hits"] >= snap1["cache_hits"] + 1
            assert snap2["encoded_chunks"] > snap1["encoded_chunks"]
        finally:
            a.close()
            b.close()

    def test_raw_verdict_is_cached_too(self):
        a, b = _pair(wire_compress_codec="dict")
        try:
            bid = ShuffleBlockId(0, 1, 0)
            noise = np.random.default_rng(5).integers(0, 256, 64 << 10, np.uint8).tobytes()
            b.register(bid, BytesBlock(noise))
            assert _fetch(a, [bid], [len(noise)]) == [noise]
            assert _fetch(a, [bid], [len(noise)]) == [noise]
            snap = b.server.compress_snapshot()
            assert snap["encoded_chunks"] == 0 and snap["raw_chunks"] >= 2
            # the incompressible verdict was remembered, not re-attempted ...
            assert snap["cache_hits"] >= 1
            # ... and a None verdict costs the pool no bytes
            assert b.server._encoded_pool_bytes == 0
        finally:
            a.close()
            b.close()

    def test_lru_eviction_under_tiny_cap(self):
        # spark.shuffle.tpu.compress.cacheBytes caps the pool; 1 byte forces
        # an eviction on every insertion
        a, b = _pair(wire_compress_codec="rle", compress_cache_bytes=1)
        try:
            bids = [ShuffleBlockId(0, i, 0) for i in range(3)]
            payloads = [bytes([i]) * (32 << 10) for i in range(3)]
            for bid, p in zip(bids, payloads):
                b.register(bid, BytesBlock(p))
            sizes = [len(p) for p in payloads]
            assert _fetch(a, bids, sizes) == payloads
            assert _fetch(a, bids, sizes) == payloads  # correct while thrashing
            # the cap held: at most one encoding resident at a time
            assert len(b.server._encoded_pool) <= 1
            assert b.server._encoded_pool_bytes <= max(
                len(encode_page(CODEC_RLE, p)) for p in payloads
            )
        finally:
            a.close()
            b.close()

    def test_unregister_purges_pool_no_stale_serve(self):
        """unregister_shuffle must drop the shuffle's cached encodings: a
        recycled shuffle id (the lineage cache recomputes under the same id
        space) with DIFFERENT bytes must never be served the old encoding."""
        a, b = _pair(wire_compress_codec="rle")
        try:
            keep = ShuffleBlockId(7, 0, 0)
            doomed = ShuffleBlockId(0, 0, 0)
            old = bytes([1]) * (64 << 10)
            other = bytes([2]) * (64 << 10)
            b.register(doomed, BytesBlock(old))
            b.register(keep, BytesBlock(other))
            assert _fetch(a, [doomed, keep], [len(old), len(other)]) == [old, other]
            assert any(k[0].shuffle_id == 0 for k in b.server._encoded_pool)

            b.unregister_shuffle(0)
            # shuffle 0's encodings are gone, shuffle 7's survive, and the
            # byte accounting stayed exact
            assert not any(k[0].shuffle_id == 0 for k in b.server._encoded_pool)
            assert any(k[0].shuffle_id == 7 for k in b.server._encoded_pool)
            assert b.server._encoded_pool_bytes == sum(
                len(enc) for _, enc in b.server._encoded_pool.values() if enc
            )

            # same id, fresh bytes: the serve path re-encodes, no stale hit
            fresh = bytes([3]) * (64 << 10)
            b.register(doomed, BytesBlock(fresh))
            assert _fetch(a, [doomed], [len(fresh)]) == [fresh]
        finally:
            a.close()
            b.close()


class TestCompressedReader:
    @pytest.mark.parametrize("codec", ["rle", "dict"])
    def test_credit_gate_composes_with_codec(self, codec):
        """The reader's CreditGate budgets DECODED bytes: a credit window
        smaller than the decoded stream (but >= one block) must still drain
        the whole shuffle, bit-exact, over a compressed wire."""
        from sparkucx_tpu.shuffle.reader import TpuShuffleReader

        payloads = [bytes([i]) * (32 << 10) for i in range(6)]
        a, b = _pair(wire_compress_codec=codec)
        try:
            for i, p in enumerate(payloads):
                b.register(ShuffleBlockId(0, i, 0), BytesBlock(p))
            reader = TpuShuffleReader(
                a, 1, 0, 0, 1, len(payloads),
                block_sizes=lambda m, r: len(payloads[m]),
                max_blocks_per_request=2,
                sender_of=lambda m: 2,
                credit_bytes=64 << 10,
            )
            got = []
            for blk in reader.fetch_blocks():
                got.append(bytes(blk.data))
                blk.release()
            assert got == payloads
            assert reader.metrics.remote_bytes_read == sum(map(len, payloads))
        finally:
            a.close()
            b.close()


# ----------------------------------------------------------------------
# tier (b): block quantization
# ----------------------------------------------------------------------


class TestQuantizeSpec:
    def test_width_math(self):
        q = QuantizeSpec(mode="int8", block_size=128)
        assert q.padded_width(128) == 128 and q.quantized_width(128) == 33
        assert q.padded_width(130) == 256 and q.quantized_width(130) == 66
        assert q.num_blocks(130) == 2

    def test_validation(self):
        with pytest.raises(ValueError, match="mode"):
            QuantizeSpec(mode="fp4").validate()
        with pytest.raises(ValueError, match="multiple of 4"):
            QuantizeSpec(mode="int8", block_size=6).validate()
        with pytest.raises(ValueError, match="multiple of 4"):
            QuantizeSpec(mode="int8", block_size=0).validate()
        with pytest.raises(ValueError, match="quantize_mode"):
            TpuShuffleConf(quantize_mode="fp4").validate()

    def test_from_conf(self):
        conf = TpuShuffleConf(quantize_mode="blockfloat", quantize_block_size=32)
        q = QuantizeSpec.from_conf(conf)
        assert q.mode == "blockfloat" and q.block_size == 32 and q.enabled
        assert not QuantizeSpec.from_conf(TpuShuffleConf()).enabled

    def test_off_mode_rejected_at_runtime(self):
        q = QuantizeSpec()
        with pytest.raises(ValueError, match="off"):
            quantize_rows(q, np.zeros((2, 8), np.float32))
        with pytest.raises(ValueError, match="off"):
            dequantize_rows(q, np.zeros((2, 3), np.int32), 8)


class TestQuantizeRows:
    @pytest.mark.parametrize("mode", ["int8", "blockfloat"])
    @pytest.mark.parametrize("w", [8, 30])  # exact blocks and padded blocks
    def test_error_within_bound_per_block(self, mode, w, rng):
        q = QuantizeSpec(mode=mode, block_size=8)
        x = rng.normal(scale=10.0, size=(64, w)).astype(np.float32)
        out = np.asarray(dequantize_rows(q, quantize_rows(q, x), w))
        assert out.shape == x.shape
        wq, bs = q.padded_width(w), q.block_size
        xp = np.pad(x, ((0, 0), (0, wq - w)))
        amax = np.abs(xp.reshape(64, -1, bs)).max(axis=2)
        bound = np.vectorize(q.error_bound)(amax) + 1e-7
        err = np.abs(out - x)
        assert (err <= np.repeat(bound, bs, axis=1)[:, :w]).all()

    @pytest.mark.parametrize("mode", ["int8", "blockfloat"])
    def test_grid_values_roundtrip_exactly(self, mode, rng):
        # values already on the int8 x pow2-scale grid quantize losslessly:
        # amax = 127 * 2^-3 makes both scales exactly 2^-3
        q = QuantizeSpec(mode=mode, block_size=8)
        levels = rng.integers(-126, 127, size=(16, 8)).astype(np.float32)
        levels[:, 0] = 127  # pin every block's amax
        x = levels * np.float32(0.125)
        out = np.asarray(dequantize_rows(q, quantize_rows(q, x), 8))
        np.testing.assert_array_equal(out, x)

    def test_zero_rows_stay_zero(self):
        q = QuantizeSpec(mode="int8", block_size=8)
        x = np.zeros((4, 16), np.float32)
        assert not np.asarray(dequantize_rows(q, quantize_rows(q, x), 16)).any()

    def test_rows_survive_permutation(self, rng):
        """Each row carries its own scales, so quantized rows can be permuted
        (the exchange moves rows) before dequantizing."""
        q = QuantizeSpec(mode="int8", block_size=8)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        qrows = np.asarray(quantize_rows(q, x))
        perm = rng.permutation(32)
        a = np.asarray(dequantize_rows(q, qrows[perm], 8))
        b = np.asarray(dequantize_rows(q, qrows, 8))[perm]
        np.testing.assert_array_equal(a, b)

    def test_payload_width_checked(self):
        q = QuantizeSpec(mode="int8", block_size=8)
        with pytest.raises(ValueError, match="quantized_width"):
            dequantize_rows(q, np.zeros((2, 5), np.int32), 8)


# ----------------------------------------------------------------------
# quantized exchange builders (4-way CPU mesh)
# ----------------------------------------------------------------------

_needs4 = pytest.mark.skipif(
    jax.device_count() < 4, reason="needs a 4-device mesh (conftest forces 8)"
)


@_needs4
class TestQuantizedExchange:
    N, SLOT, LANE = 4, 8, 8

    def _case(self, rng):
        n, slot = self.N, self.SLOT
        data = rng.normal(scale=5.0, size=(n * n * slot, self.LANE)).astype(np.float32)
        sizes = rng.integers(0, slot + 1, size=(n, n)).astype(np.int32)
        return data, sizes

    def _spec_mesh(self):
        from sparkucx_tpu.ops.exchange import ExchangeSpec, make_mesh

        spec = ExchangeSpec(
            num_executors=self.N, send_rows=self.N * self.SLOT,
            recv_rows=self.N * self.SLOT, lane=self.LANE,
        )
        return spec, make_mesh(self.N)

    def _run(self, fn, mesh, data, sizes):
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P("ex", None))
        recv, rs = fn(jax.device_put(data, sharding), jax.device_put(sizes, sharding))
        return np.asarray(recv), np.asarray(rs)

    @pytest.mark.parametrize("mode", ["int8", "blockfloat"])
    def test_within_bound_vs_stock(self, mode, rng):
        from sparkucx_tpu.ops.exchange import build_exchange
        from sparkucx_tpu.ops.ici_exchange import build_quantized_exchange

        q = QuantizeSpec(mode=mode, block_size=8)
        spec, mesh = self._spec_mesh()
        data, sizes = self._case(rng)
        recv_ref, rs_ref = self._run(
            build_exchange(mesh, spec), mesh, data.view(np.int32).copy(), sizes
        )
        recv_q, rs_q = self._run(
            build_quantized_exchange(mesh, spec, q), mesh, data, sizes
        )
        np.testing.assert_array_equal(rs_ref, rs_q)
        bound = q.error_bound(float(np.abs(data).max())) + 1e-7
        assert np.abs(recv_q - recv_ref.view(np.float32)).max() <= bound

    def test_fused_matches_unfused(self, rng):
        """Scatter + quantize + ring in one jit equals staging first and
        running the unfused quantized exchange — bit-identical (same staged
        rows, deterministic quantizer)."""
        from sparkucx_tpu.ops.ici_exchange import (
            build_quantized_exchange,
            build_quantized_fused_exchange,
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        q = QuantizeSpec(mode="int8", block_size=8)
        spec, mesh = self._spec_mesh()
        n, slot, send_rows = self.N, self.SLOT, self.N * self.SLOT
        sizes = rng.integers(1, slot + 1, size=(n, n)).astype(np.int32)
        starts = np.zeros((n, n), np.int32)
        counts = np.zeros((n, n), np.int32)
        outs = np.zeros((n, n), np.int32)
        packed = np.zeros((n * send_rows, self.LANE), np.float32)
        staged_ref = np.zeros((n * send_rows, self.LANE), np.float32)
        for i in range(n):
            off = 0
            for j in range(n):
                c = int(sizes[i, j])
                rows = rng.normal(size=(c, self.LANE)).astype(np.float32)
                packed[i * send_rows + off : i * send_rows + off + c] = rows
                staged_ref[i * send_rows + j * slot : i * send_rows + j * slot + c] = rows
                starts[i, j], counts[i, j], outs[i, j] = j * slot, c, off
                off += c
        sharding = NamedSharding(mesh, P("ex", None))
        put = lambda a: jax.device_put(a, sharding)
        recv_u, rs_u = build_quantized_exchange(mesh, spec, q)(
            put(staged_ref), put(sizes)
        )
        recv_f, rs_f = build_quantized_fused_exchange(
            mesh, spec, q, n, max_block_rows=slot
        )(
            put(starts), put(counts), put(outs), put(packed),
            put(np.zeros((n * send_rows, self.LANE), np.float32)), put(sizes),
        )
        np.testing.assert_array_equal(np.asarray(rs_u), np.asarray(rs_f))
        assert np.asarray(recv_u).tobytes() == np.asarray(recv_f).tobytes()

    def test_builder_rejections(self):
        from sparkucx_tpu.ops.exchange import ExchangeSpec, make_mesh
        from sparkucx_tpu.ops.hierarchy import make_hierarchical_mesh
        from sparkucx_tpu.ops.ici_exchange import build_quantized_exchange

        spec, mesh = self._spec_mesh()
        q = QuantizeSpec(mode="int8", block_size=8)
        with pytest.raises(ValueError, match="flat"):
            build_quantized_exchange(
                make_hierarchical_mesh(2, 4),
                ExchangeSpec(num_executors=8, send_rows=64, recv_rows=64, lane=8),
                q,
            )
        with pytest.raises(ValueError, match="int8"):
            build_quantized_exchange(mesh, spec, QuantizeSpec())
        with pytest.raises(ValueError, match="num_executors > 1"):
            build_quantized_exchange(
                make_mesh(1),
                ExchangeSpec(num_executors=1, send_rows=8, recv_rows=8, lane=8),
                q,
            )


# ----------------------------------------------------------------------
# groupby partial-aggregate quantization (ops/relational.py)
# ----------------------------------------------------------------------


class TestAggregateQuantize:
    def _spec(self, **kw):
        from sparkucx_tpu.ops.relational import AggregateSpec

        kw.setdefault("num_executors", 4)
        kw.setdefault("capacity", 64)
        kw.setdefault("recv_capacity", 256)
        kw.setdefault("aggs", ("sum",))
        kw.setdefault("impl", "dense")
        return AggregateSpec(**kw)

    def test_validate_requires_partial_and_float(self):
        with pytest.raises(ValueError, match="partial"):
            self._spec(
                quantize_mode="int8", dtype=np.dtype(np.float32), partial=False
            ).validate()
        with pytest.raises(ValueError, match="floating"):
            self._spec(quantize_mode="int8", partial=True).validate()  # int32 dtype
        with pytest.raises(ValueError, match="mode"):
            self._spec(
                quantize_mode="fp4", dtype=np.dtype(np.float32), partial=True
            ).validate()
        # the applicable combination passes
        self._spec(
            quantize_mode="blockfloat", dtype=np.dtype(np.float32), partial=True
        ).validate()

    def test_from_conf_silently_skips_inapplicable_plans(self):
        from sparkucx_tpu.ops.relational import AggregateSpec

        conf = TpuShuffleConf(quantize_mode="int8", partial_aggregation=False)
        spec = AggregateSpec.from_conf(
            conf, capacity=64, recv_capacity=256, aggs=("sum",), impl="dense"
        )
        # cluster knob on, plan not partial/float: stock path, no error
        assert spec.quantize_mode == "off"
        spec.validate()
        # an EXPLICIT quantize_mode kwarg is never silently dropped
        spec2 = AggregateSpec.from_conf(
            conf, capacity=64, recv_capacity=256, aggs=("sum",), impl="dense",
            quantize_mode="int8",
        )
        assert spec2.quantize_mode == "int8"
        with pytest.raises(ValueError):
            spec2.validate()

    def test_from_conf_applies_to_partial_float_plans(self):
        from sparkucx_tpu.ops.relational import AggregateSpec

        conf = TpuShuffleConf(quantize_mode="blockfloat", quantize_block_size=32)
        spec = AggregateSpec.from_conf(
            conf, capacity=64, recv_capacity=256, aggs=("sum",), impl="dense",
            partial=True, dtype=np.dtype(np.float32),
        )
        assert spec.quantize_mode == "blockfloat" and spec.quantize_block_size == 32
        spec.validate()

    @_needs4
    @pytest.mark.parametrize("mode", ["int8", "blockfloat"])
    def test_lossy_groupby_within_tolerance(self, mode, rng):
        """The dequant-tolerance gate: a quantized partial-aggregate groupby
        stays within N partials x error_bound of the exact oracle, with keys
        and counts EXACT (they are never quantized)."""
        from sparkucx_tpu.ops.exchange import make_mesh
        from sparkucx_tpu.ops.relational import oracle_aggregate, run_grouped_aggregate

        n, total = 4, 1500
        keys = rng.integers(0, 40, size=total).astype(np.uint32)
        # positive values: partial sums stay below the full sum, so the
        # oracle's max value bounds every partial block's amax
        values = rng.uniform(0.1, 1.0, size=(total, 2)).astype(np.float32)
        spec = self._spec(
            capacity=512, recv_capacity=1024, aggs=("sum", "max"),
            dtype=np.dtype(np.float32), partial=True, quantize_mode=mode,
            quantize_block_size=4,
        )
        gk, gv, gc = run_grouped_aggregate(make_mesh(n), spec, keys, values)
        ok, ov, oc = oracle_aggregate(keys, values, ("sum", "max"))
        np.testing.assert_array_equal(gk, ok)  # group identity exact
        np.testing.assert_array_equal(gc, oc)  # COUNT exact
        q = spec.qspec
        atol = n * q.error_bound(float(np.abs(ov).max())) + 1e-5
        np.testing.assert_allclose(gv, ov, atol=atol)

    @_needs4
    def test_quantize_off_is_bit_identical_to_stock(self, rng):
        from sparkucx_tpu.ops.exchange import make_mesh
        from sparkucx_tpu.ops.relational import run_grouped_aggregate

        n, total = 4, 800
        keys = rng.integers(0, 30, size=total).astype(np.uint32)
        values = rng.normal(size=(total, 2)).astype(np.float32)
        base = self._spec(
            capacity=512, recv_capacity=1024, aggs=("sum", "max"),
            dtype=np.dtype(np.float32), partial=True,
        )
        off = self._spec(
            capacity=512, recv_capacity=1024, aggs=("sum", "max"),
            dtype=np.dtype(np.float32), partial=True, quantize_mode="off",
        )
        gk1, gv1, gc1 = run_grouped_aggregate(make_mesh(n), base, keys, values)
        gk2, gv2, gc2 = run_grouped_aggregate(make_mesh(n), off, keys, values)
        assert gv1.tobytes() == gv2.tobytes()
        np.testing.assert_array_equal(gk1, gk2)
        np.testing.assert_array_equal(gc1, gc2)
