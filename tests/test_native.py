"""Tests for the native arena (C++ via ctypes): build, pinned buffers,
shared-memory cross-process visibility, batch copy."""

import os
import subprocess
import sys

import numpy as np
import pytest

from sparkucx_tpu import native


pytestmark = pytest.mark.skipif(
    not native.native_available(), reason=f"native build unavailable: {native.build_error()}"
)


class TestPinnedBuffer:
    def test_alloc_and_alignment(self):
        with native.PinnedBuffer(1 << 20, alignment=4096) as buf:
            assert buf.array.size == 1 << 20
            assert buf.array.ctypes.data % 4096 == 0
            buf.array[:100] = 7
            assert (buf.array[:100] == 7).all()

    def test_close_idempotent(self):
        buf = native.PinnedBuffer(4096)
        buf.close()
        buf.close()


class TestSharedArena:
    def test_create_write_attach_read(self):
        name = f"/ts_test_{os.getpid()}"
        with native.SharedArena(name, 1 << 16, create=True) as arena:
            arena.array[:256] = np.arange(256, dtype=np.uint8)
            with native.SharedArena(name, 1 << 16, create=False) as attached:
                assert (attached.array[:256] == np.arange(256, dtype=np.uint8)).all()
                attached.array[0] = 99
                assert arena.array[0] == 99

    def test_cross_process_visibility(self):
        name = f"/ts_xproc_{os.getpid()}"
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with native.SharedArena(name, 4096, create=True) as arena:
            arena.array[:5] = [1, 2, 3, 4, 5]
            script = (
                f"import sys; sys.path.insert(0, {root!r});\n"
                "from sparkucx_tpu import native\n"
                f"a = native.SharedArena({name!r}, 4096, create=False)\n"
                "print([int(x) for x in a.array[:5]])\n"
                "a.array[5] = 42\n"
                "a.close()\n"
            )
            out = subprocess.run(
                [sys.executable, "-c", script], capture_output=True, text=True, timeout=60
            )
            assert out.returncode == 0, out.stderr
            assert "[1, 2, 3, 4, 5]" in out.stdout
            assert arena.array[5] == 42

    def test_attach_missing_fails(self):
        with pytest.raises(OSError):
            native.SharedArena("/ts_does_not_exist_xyz", 4096, create=False)

    def test_double_create_fails(self):
        name = f"/ts_dup_{os.getpid()}"
        with native.SharedArena(name, 4096, create=True):
            with pytest.raises(OSError):
                native.SharedArena(name, 4096, create=True)


class TestBatchCopy:
    def test_scattered_segments(self, rng):
        src = rng.integers(0, 256, size=1 << 16, dtype=np.uint8)
        dst = np.zeros(1 << 16, dtype=np.uint8)
        segs = [(0, 1000, 500), (600, 5000, 256), (900, 0, 128)]
        native.batch_copy(dst, src, segs)
        for d, s, l in segs:
            assert (dst[d : d + l] == src[s : s + l]).all()

    def test_large_threaded_copy(self, rng):
        # > 4 MiB total triggers the thread team
        src = rng.integers(0, 256, size=16 << 20, dtype=np.uint8)
        dst = np.zeros(16 << 20, dtype=np.uint8)
        seg_len = 1 << 20
        segs = [(i * seg_len, (15 - i) * seg_len, seg_len) for i in range(16)]
        native.batch_copy(dst, src, segs, max_threads=4)
        for d, s, l in segs:
            assert (dst[d : d + l] == src[s : s + l]).all()

    def test_python_fallback_matches(self, rng, monkeypatch):
        src = rng.integers(0, 256, size=4096, dtype=np.uint8)
        dst_native = np.zeros(4096, dtype=np.uint8)
        dst_py = np.zeros(4096, dtype=np.uint8)
        segs = [(0, 2048, 1024), (2048, 0, 512)]
        native.batch_copy(dst_native, src, segs)
        monkeypatch.setattr(native, "_load", lambda: None)
        native.batch_copy(dst_py, src, segs)
        assert (dst_native == dst_py).all()


def test_version():
    assert native._load().ts_version() == 1


class TestShmStore:
    def test_store_with_shm_staging(self):
        from sparkucx_tpu.config import TpuShuffleConf
        from sparkucx_tpu.store.hbm_store import HbmBlockStore

        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 18,
            use_shm_staging=True,
            shm_namespace=f"ts_store_{os.getpid()}",
        )
        store = HbmBlockStore(conf, executor_id=3)
        try:
            store.create_shuffle(0, 1, 2)
            w = store.map_writer(0, 0)
            w.write_partition(0, b"shm-staged")
            w.commit()
            assert store.read_block(0, 0, 0) == b"shm-staged"
            # another process attaches the same named arena and sees the bytes
            name = f"/{conf.shm_namespace}_e3_s0"
            with native.SharedArena(name, 4096, create=False) as peer:
                assert bytes(peer.array[:10]) == b"shm-staged"
        finally:
            store.close()
        # unlinked at close: attach must now fail
        with pytest.raises(OSError):
            native.SharedArena(name, 4096, create=False)
