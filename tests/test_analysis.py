"""Analyzer + sanitizer tests (fast, no device work).

Each analysis pass gets three fixture snippets run through ``run_source``:
one that must flag, one that must stay clean, and one exercising the escape
hatch (allowlist / lock held / bucketing rebind / docstring contract).  The
fixtures are source STRINGS — they are parsed, never imported, so they can
reference modules that don't exist.

The sanitizer tests pin the documented lifecycle contracts: release is
idempotent in normal mode; sanitize mode raises on double-release,
use-after-release, and re-pooling with live exported views, and poisons
freed host buffers with 0xDD.
"""

import os
import textwrap

import numpy as np
import pytest

from sparkucx_tpu.analysis import is_allowlisted, run_source
from sparkucx_tpu.analysis.__main__ import main as analysis_main
from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import ShuffleBlockId
from sparkucx_tpu.memory.pool import MemoryPool
from sparkucx_tpu.memory.sanitizer import POISON, BufferSanitizer, SanitizerError
from sparkucx_tpu.shuffle.reader import BlockFetchResult


def src(text: str) -> str:
    return textwrap.dedent(text)


def messages(findings):
    return [f.message for f in findings]


# ----------------------------------------------------------------------
# use-after-donate


class TestUseAfterDonate:
    def test_flags_read_after_donating_call(self):
        findings = run_source(
            src(
                """
                def run(spec, buf):
                    fn = build_exchange(spec)
                    out = fn(buf)
                    return buf.sum() + out
                """
            ),
            passes=["use-after-donate"],
        )
        assert len(findings) == 1
        assert "buf" in findings[0].message
        assert "donated" in findings[0].message

    def test_flags_jit_donate_argnums(self):
        findings = run_source(
            src(
                """
                import jax

                def run(x):
                    g = jax.jit(step, donate_argnums=(0,))
                    y = g(x)
                    return x + y
                """
            ),
            passes=["use-after-donate"],
        )
        assert len(findings) == 1
        assert "x" in findings[0].message

    def test_rebind_revives_and_branches_merge(self):
        # rebinding the name after donation makes later reads legal; a read
        # that only happens on the non-donating branch is also legal
        findings = run_source(
            src(
                """
                def run(spec, buf, cond):
                    fn = build_exchange(spec)
                    buf = fn(buf)
                    return buf.sum()

                def branchy(spec, buf, cond):
                    fn = build_exchange(spec)
                    if cond:
                        fn(buf)
                    else:
                        pass
                    return buf
                """
            ),
            passes=["use-after-donate"],
        )
        # `return buf` after the If IS flagged (donated on one branch ->
        # may-donate is must-not-reuse), but `buf = fn(buf)` is not
        assert len(findings) == 1
        assert findings[0].line > 7

    def test_block_scatter_positional_donation(self):
        findings = run_source(
            src(
                """
                def run(b, out):
                    fn = build_block_scatter(1, 2, 3, 4)
                    fn(a, b, c, d, out)
                    return out
                """
            ),
            passes=["use-after-donate"],
        )
        assert len(findings) == 1
        assert "out" in findings[0].message

    def test_ici_exchange_donates_staging(self):
        """The scheduled-exchange builders (ops/ici_exchange.py) carry the
        same donation contracts as their stock counterparts: arg 0 of the
        plain exchange, the staging buffer (arg 4) of the fused send side."""
        findings = run_source(
            src(
                """
                def run(mesh, spec, data, sizes, staging):
                    fn = build_ici_exchange(mesh, spec)
                    fn(data, sizes)
                    fused = build_fused_ici_exchange(mesh, spec, 8)
                    fused(a, b, c, d, staging, sizes)
                    return data.sum() + staging.sum()
                """
            ),
            passes=["use-after-donate"],
        )
        assert len(findings) == 2
        assert any("data" in f.message for f in findings)
        assert any("staging" in f.message for f in findings)


# ----------------------------------------------------------------------
# lock-discipline


LOCK_FIXTURE = """
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._other_lock = threading.Lock()
        self._items = []  #: guarded by self._lock

    def bad(self, x):
        self._items.append(x)

    def wrong_lock(self, x):
        with self._other_lock:
            self._items = [x]

    def good(self, x):
        with self._lock:
            self._items.append(x)

    def helper(self, x):
        \"\"\"Append one item; caller holds ``self._lock``.\"\"\"
        self._items.append(x)
"""


class TestLockDiscipline:
    def test_flags_unguarded_and_wrong_lock(self):
        findings = run_source(src(LOCK_FIXTURE), passes=["lock-discipline"])
        assert len(findings) == 2
        assert any("mutator call '.append()'" in m for m in messages(findings))
        assert any("_other_lock" in m for m in messages(findings))

    def test_init_and_caller_holds_exempt(self):
        findings = run_source(src(LOCK_FIXTURE), passes=["lock-discipline"])
        lines = {f.line for f in findings}
        # __init__ assignment (the annotation line) and the documented helper
        # must not be among the findings
        assert all(l < 20 for l in lines)

    def test_clean_without_annotations(self):
        findings = run_source(
            src(
                """
                class Free:
                    def mutate(self, x):
                        self.items.append(x)
                """
            ),
            passes=["lock-discipline"],
        )
        assert findings == []

    def test_flags_unguarded_credit_counter(self):
        # The striped-wire CreditGate pattern: a byte counter annotated as
        # guarded by a Condition named _lock.  Mutating it without the lock
        # (the augmented-assign form the accounting paths use) must flag;
        # the guarded twin must not.
        findings = run_source(
            src(
                """
                import threading

                class Gate:
                    def __init__(self, budget):
                        self.budget = budget
                        self._lock = threading.Condition()
                        self._used = 0  #: guarded by self._lock

                    def release_racy(self, n):
                        self._used -= n

                    def release(self, n):
                        with self._lock:
                            self._used -= n
                            self._lock.notify_all()
                """
            ),
            passes=["lock-discipline"],
        )
        assert len(findings) == 1
        assert "_used" in findings[0].message


# ----------------------------------------------------------------------
# host-sync


HOSTSYNC_FIXTURE = """
import numpy as np
from sparkucx_tpu.transport.pipeline import RoundPipeline

class Exchanger:
    def _submit(self, r):
        x = self._arrs[r]
        x.block_until_ready()
        return x

    def _drain(self, r, ticket):
        return np.asarray(ticket)

    def _helper(self, t):
        return jax.device_get(t)

    def _run_exchange(self, rounds):
        pipe = RoundPipeline(2, self._submit, self._drain, name="x")
        for r in range(rounds):
            self._helper(r)

    def unrelated(self, x):
        x.block_until_ready()
"""


class TestHostSync:
    def test_flags_stages_and_reachable_callees(self):
        findings = run_source(src(HOSTSYNC_FIXTURE), passes=["host-sync"])
        msgs = messages(findings)
        assert any("block_until_ready" in m and "submit stage" in m for m in msgs)
        assert any("np.asarray" in m and "drain stage" in m for m in msgs)
        assert any("device_get" in m and "via '_helper'" in m for m in msgs)
        # `unrelated` is not a stage and not reachable from _run_exchange
        assert not any("unrelated" in m for m in msgs)
        assert len(findings) == 3

    def test_literal_asarray_not_flagged(self):
        findings = run_source(
            src(
                """
                import numpy as np
                from sparkucx_tpu.transport.pipeline import RoundPipeline

                class E:
                    def _submit(self, r):
                        return np.asarray([0, 1, 2])

                    def _drain(self, r, t):
                        return t

                    def go(self):
                        RoundPipeline(2, self._submit, self._drain)
                """
            ),
            passes=["host-sync"],
        )
        assert findings == []

    def test_drain_findings_are_allowlistable_by_lane(self):
        findings = run_source(
            src(HOSTSYNC_FIXTURE), passes=["host-sync"], filename="transport/fix.py"
        )
        allow = {("transport/fix.py", "host-sync", "drain stage")}
        left = [f for f in findings if not is_allowlisted(f, allow)]
        # the drain-lane finding is suppressed; submit + root survive
        assert len(left) == 2
        assert all("drain stage" not in f.message for f in left)


# ----------------------------------------------------------------------
# cache-hygiene


class TestCacheHygiene:
    def test_flags_raw_shape_params_in_cache_key(self):
        findings = run_source(
            src(
                """
                class S:
                    def get(self, rows, width):
                        key = (rows, width)
                        if key not in self._scatter_cache:
                            self._scatter_cache[key] = build_thing(rows, width)
                        return self._scatter_cache[key]
                """
            ),
            passes=["cache-hygiene"],
        )
        msgs = messages(findings)
        assert any("'rows'" in m for m in msgs)
        assert any("'width'" in m for m in msgs)

    def test_bucketed_param_clean(self):
        findings = run_source(
            src(
                """
                class S:
                    def get(self, rows, width):
                        rows = round_up_to_next_power_of_two(rows)
                        width = bucket_send_rows(width)
                        key = (rows, width)
                        if key not in self._scatter_cache:
                            self._scatter_cache[key] = build_thing(rows, width)
                        return self._scatter_cache[key]
                """
            ),
            passes=["cache-hygiene"],
        )
        assert findings == []

    def test_skew_planner_rebind_counts_as_bucketed(self):
        """Shape params flowing through the skew planner (quota_slot_rows /
        plan_exchange, ops/skew.py) are pow2-bucketed by construction and
        must sanctify a cache key like bucket_send_rows does."""
        findings = run_source(
            src(
                """
                class S:
                    def get(self, rows, depth):
                        rows = quota_slot_rows(rows, self.conf.slot_quota_rows)
                        depth = plan_exchange([depth], depth, 0).slot_rows
                        key = (rows, depth)
                        if key not in self._exchange_cache:
                            self._exchange_cache[key] = build_thing(rows, depth)
                        return self._exchange_cache[key]
                """
            ),
            passes=["cache-hygiene"],
        )
        assert findings == []

    def test_ici_cache_raw_shape_key_flagged(self):
        """A compiled-schedule cache in front of build_ici_exchange keyed on
        raw send_rows is the same recompile bomb the exchange cache pass
        exists to catch — ISSUE 6's cache must stay pow2-bucketed."""
        findings = run_source(
            src(
                """
                class T:
                    def get(self, send_rows, chunks):
                        key = (send_rows, chunks)
                        if key not in self._ici_cache:
                            self._ici_cache[key] = build_ici_exchange(
                                self.mesh, make_spec(send_rows), chunks_per_dest=chunks
                            )
                        return self._ici_cache[key]
                """
            ),
            passes=["cache-hygiene"],
        )
        msgs = messages(findings)
        assert any("'send_rows'" in m for m in msgs)

    def test_ici_cache_bucketed_rebind_clean(self):
        """bucket_send_rows sanctifies the slot geometry and schedule_chunks
        (the pow2 chunk-count clamp, BUCKETING_MARKERS) sanctifies the chunk
        key — the shape the real transports put in front of the cache."""
        findings = run_source(
            src(
                """
                class T:
                    def get(self, send_rows, chunks):
                        send_rows = bucket_send_rows(send_rows, self.n)
                        chunks = schedule_chunks(send_rows // self.n, chunks)
                        key = (send_rows, chunks)
                        if key not in self._ici_cache:
                            self._ici_cache[key] = build_ici_exchange(
                                self.mesh, make_spec(send_rows), chunks_per_dest=chunks
                            )
                        return self._ici_cache[key]
                """
            ),
            passes=["cache-hygiene"],
        )
        assert findings == []

    def test_lru_cache_builder_flagged(self):
        findings = run_source(
            src(
                """
                import functools

                @functools.lru_cache(maxsize=None)
                def build_gather(num_blocks, dtype):
                    return num_blocks
                """
            ),
            passes=["cache-hygiene"],
        )
        assert len(findings) == 1
        assert "num_blocks" in findings[0].message
        assert "bucket" in findings[0].message


# ----------------------------------------------------------------------
# private-access / required-surface / allowlist mechanics


class TestPrivateAndSurface:
    def test_private_access_flagged_self_ok(self):
        findings = run_source(
            src(
                """
                def f(other):
                    return other._guts

                class C:
                    def g(self):
                        return self._mine
                """
            ),
            passes=["private-access"],
        )
        assert len(findings) == 1
        assert "._guts" in findings[0].message

    def test_required_surface_missing_method(self):
        findings = run_source(
            src(
                """
                class HbmBlockStore:
                    def register_shuffle(self):
                        pass
                """
            ),
            passes=["required-surface"],
            filename="store/hbm_store.py",
        )
        assert any("missing" in m for m in messages(findings))

    def test_allowlist_matching_is_narrow(self):
        findings = run_source(
            "def f(o):\n    return o._guts\n",
            passes=["private-access"],
            filename="transport/thing.py",
        )
        (f,) = findings
        assert is_allowlisted(f, {("transport/thing.py", "private-access", "._guts")})
        assert is_allowlisted(f, {("thing.py", "*", "._guts")})
        assert not is_allowlisted(f, {("other.py", "private-access", "._guts")})
        assert not is_allowlisted(f, {("thing.py", "lock-discipline", "._guts")})
        assert not is_allowlisted(f, {("thing.py", "private-access", "._other")})


# ----------------------------------------------------------------------
# lock-order (whole-program pass)


class TestLockOrder:
    def test_flags_inverted_acquisition_order(self):
        findings = run_source(
            src(
                """
                class Store:
                    def fwd(self):
                        with self._lock:
                            with self._order_lock:
                                pass

                    def rev(self):
                        with self._order_lock:
                            with self._lock:
                                pass
                """
            ),
            passes=["lock-order"],
        )
        assert len(findings) == 1
        assert "lock-order cycle" in findings[0].message
        assert "Store._lock" in findings[0].message
        assert "Store._order_lock" in findings[0].message

    def test_flags_transitive_self_reacquire(self):
        # get() holds the lock and calls a helper that takes it again — the
        # classic non-reentrant-Lock deadlock, visible only through the call
        # summary, not lexically.
        findings = run_source(
            src(
                """
                class Pool:
                    def get(self):
                        with self._lock:
                            return self._allocate_more()

                    def _allocate_more(self):
                        with self._lock:
                            return 1
                """
            ),
            passes=["lock-order"],
        )
        assert len(findings) == 1
        assert "self-cycle" in findings[0].message
        assert "Pool._lock" in findings[0].message

    def test_flags_blocking_call_under_lock(self):
        findings = run_source(
            src(
                """
                class Tx:
                    def send(self, sock, data):
                        with self._lock:
                            sock.sendall(data)
                """
            ),
            passes=["lock-order"],
        )
        assert len(findings) == 1
        assert "blocking call 'sendall'" in findings[0].message
        assert "Tx._lock" in findings[0].message

    def test_consistent_order_clean(self):
        findings = run_source(
            src(
                """
                class Ok:
                    def a(self):
                        with self._lock:
                            with self._inner_lock:
                                pass

                    def b(self):
                        with self._lock:
                            x = compute()
                            with self._inner_lock:
                                use(x)
                """
            ),
            passes=["lock-order"],
        )
        assert findings == []

    def test_send_lock_exempt_from_blocking_check(self):
        # LOCK_BLOCKING_EXEMPT wildcards *.send_lock: serializing a blocking
        # frame write IS that lock's documented job.
        findings = run_source(
            src(
                """
                class Conn:
                    def write(self, sock, data):
                        with self.send_lock:
                            sock.sendall(data)
                """
            ),
            passes=["lock-order"],
        )
        assert findings == []

    def test_closure_lock_use_invisible(self):
        # Documented limit: a nested def's body runs later, on another
        # thread — its lock use must NOT count as the enclosing method's
        # (the pool.py recycle-closure shape that false-positived as a
        # self-cycle during development).
        findings = run_source(
            src(
                """
                class P:
                    def get(self):
                        with self._lock:
                            def recycle():
                                with self._lock:
                                    pass
                            return recycle
                """
            ),
            passes=["lock-order"],
        )
        assert findings == []

    def test_cross_object_edges_and_dot(self):
        import ast as ast_mod

        from sparkucx_tpu.analysis.base import Program
        from sparkucx_tpu.analysis.lockorder import build_lock_graph, render_dot

        srcs = {
            "transport/peer.py": src(
                """
                class PeerTransport:
                    def seal(self):
                        with self._tag_lock:
                            return self.store.num_rounds()
                """
            ),
            "store/hbm_store.py": src(
                """
                class HbmBlockStore:
                    def num_rounds(self):
                        with self._lock:
                            return 1
                """
            ),
        }
        program = Program(
            modules={k: (ast_mod.parse(v), v) for k, v in srcs.items()},
            docs={},
            tests_text="",
        )
        edges, blocking = build_lock_graph(program)
        # self.store.* resolves through LOCK_ATTR_CLASSES to HbmBlockStore
        assert ("PeerTransport._tag_lock", "HbmBlockStore._lock") in edges
        assert blocking == []
        dot = render_dot(edges)
        assert dot.startswith("digraph lock_order")
        assert '"PeerTransport._tag_lock" -> "HbmBlockStore._lock"' in dot


# ----------------------------------------------------------------------
# reactor-discipline


class TestReactorDiscipline:
    def test_loop_lane_flags_blocking_socket_op_via_chain(self):
        findings = run_source(
            src(
                """
                class Server:
                    def start(self, reactor):
                        reactor.add_listener(self._sock, self._on_accept)

                    def _on_accept(self):
                        self._drain()

                    def _drain(self):
                        return self._sock.recv(4096)
                """
            ),
            passes=["reactor-discipline"],
        )
        assert len(findings) == 1
        assert "blocking socket op 'recv'" in findings[0].message
        assert "loop" in findings[0].message
        assert "(via '_on_accept')" in findings[0].message

    def test_worker_lane_allows_reads_but_flags_join(self):
        findings = run_source(
            src(
                """
                class Conn:
                    def start(self, reactor):
                        reactor.add_connection(self, self._serve, on_close=self._closed)

                    def _serve(self):
                        return self._sock.recv(4096)

                    def _closed(self):
                        self._thread.join()
                """
            ),
            passes=["reactor-discipline"],
        )
        # blocking frame reads are the worker lane's documented design;
        # an untimed join can deadlock the pool against itself
        assert len(findings) == 1
        assert "'join()' without timeout" in findings[0].message
        assert "worker" in findings[0].message

    def test_escape_comment(self):
        findings = run_source(
            src(
                """
                class Server:
                    def start(self, reactor):
                        reactor.add_listener(self._sock, self._on_accept)

                    def _on_accept(self):
                        return self._sock.recv(4096)  #: reactor-ok
                """
            ),
            passes=["reactor-discipline"],
        )
        assert findings == []

    def test_module_without_registrations_ignored(self):
        findings = run_source(
            src(
                """
                class Plain:
                    def fetch(self):
                        return self._sock.recv(4096)
                """
            ),
            passes=["reactor-discipline"],
        )
        assert findings == []


# ----------------------------------------------------------------------
# thread-lifecycle


class TestThreadLifecycle:
    def test_flags_nondaemon_unjoined_thread(self):
        findings = run_source(
            src(
                """
                import threading

                def start(work):
                    t = threading.Thread(target=work)
                    t.start()
                    return t
                """
            ),
            passes=["thread-lifecycle"],
        )
        assert len(findings) == 1
        assert "never joined" in findings[0].message
        assert "'t'" in findings[0].message

    def test_daemon_joined_and_spawn_list_idioms_clean(self):
        findings = run_source(
            src(
                """
                import threading

                def daemonized(work):
                    t = threading.Thread(target=work, daemon=True)
                    t.start()

                def reaped(work):
                    t = threading.Thread(target=work)
                    t.start()
                    t.join()

                def harness(work, n):
                    threads = [threading.Thread(target=work) for _ in range(n)]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                """
            ),
            passes=["thread-lifecycle"],
        )
        assert findings == []

    def test_queue_bounds(self):
        findings = run_source(
            src(
                """
                import queue

                def make():
                    a = queue.Queue()
                    b = queue.Queue(maxsize=0)
                    c = queue.SimpleQueue()
                    good = queue.Queue(maxsize=64)
                    also_good = queue.Queue(8)
                    return a, b, c, good, also_good
                """
            ),
            passes=["thread-lifecycle"],
        )
        msgs = messages(findings)
        assert len(findings) == 3
        assert sum("without a positive maxsize" in m for m in msgs) == 2
        assert sum("SimpleQueue" in m for m in msgs) == 1

    def test_escape_comment(self):
        findings = run_source(
            src(
                """
                import threading

                def start(work):
                    t = threading.Thread(target=work)  #: lifecycle: joined by the harness teardown helper
                    t.start()
                    return t
                """
            ),
            passes=["thread-lifecycle"],
        )
        assert findings == []


# ----------------------------------------------------------------------
# resource-balance


class TestResourceBalance:
    def test_flags_unbalanced_acquire(self):
        findings = run_source(
            src(
                """
                class Reader:
                    def admit(self, n):
                        self._gate.acquire(n)
                        self.do_fetch(n)
                """
            ),
            passes=["resource-balance"],
        )
        assert len(findings) == 1
        assert "self._gate.acquire" in findings[0].message
        assert "exception paths" in findings[0].message

    def test_try_finally_sibling_and_enclosing_clean(self):
        findings = run_source(
            src(
                """
                class Reader:
                    def sibling(self, n):
                        self._gate.acquire(n)
                        try:
                            self.do_fetch(n)
                        finally:
                            self._gate.release(n)

                    def enclosing(self, n):
                        try:
                            self._gate.acquire(n)
                            self.do_fetch(n)
                        finally:
                            self._gate.release(n)

                    def handler(self, st, n):
                        try:
                            self.tenants.charge(st, n)
                            self.stage(st)
                        except Exception:
                            self.tenants.release(st, n)
                            raise
                """
            ),
            passes=["resource-balance"],
        )
        assert findings == []

    def test_lock_receivers_skipped(self):
        # lock.acquire() belongs to the lock passes, not resource balance
        findings = run_source(
            src(
                """
                class C:
                    def f(self):
                        self._lock.acquire()
                        self._cond.acquire()
                """
            ),
            passes=["resource-balance"],
        )
        assert findings == []

    def test_escape_comment_and_docstring_transfer(self):
        findings = run_source(
            src(
                """
                class Store:
                    def restage(self, st, n):
                        self._charge_tenant(st, n)  #: balanced by _release_tenant
                        self.promote(st)

                    def _charge_tenant(self, st, n):
                        \"\"\"Claim quota; released by ``_release_tenant`` on removal.\"\"\"
                        self.tenants.charge(st.app_id, n)
                """
            ),
            passes=["resource-balance"],
        )
        assert findings == []

    def test_wrong_release_name_in_comment_still_flags(self):
        findings = run_source(
            src(
                """
                class Store:
                    def restage(self, st, n):
                        self._charge_tenant(st, n)  #: balanced by something_else
                """
            ),
            passes=["resource-balance"],
        )
        assert len(findings) == 1


# ----------------------------------------------------------------------
# wire-schema (whole-program pass; docs injected through run_source)


WIRE_FIXTURE = """
import struct

class AmId:
    FETCH_REQ = 0
    FETCH_ACK = 1

_HDR = struct.Struct("<IQQ")
"""

WIRE_DOC_COMPLETE = (
    "| 0 | FetchReq | request |\n"
    "| 1 | FetchAck | reply |\n"
    "frame prefix is `<IQQ>` little-endian\n"
)


class TestWireSchema:
    def test_flags_undocumented_id_and_struct(self):
        findings = run_source(
            src(WIRE_FIXTURE),
            passes=["wire-schema"],
            docs={"SHIM_PROTOCOL.md": "| 0 | FetchReq | request |\n"},
        )
        msgs = messages(findings)
        assert len(findings) == 2
        assert any("FETCH_ACK=1" in m and "FetchAck" in m for m in msgs)
        assert any("_HDR" in m and "<IQQ" in m for m in msgs)

    def test_complete_doc_clean(self):
        findings = run_source(
            src(WIRE_FIXTURE),
            passes=["wire-schema"],
            docs={"SHIM_PROTOCOL.md": WIRE_DOC_COMPLETE},
        )
        assert findings == []

    def test_duplicate_and_gap_values_flagged_without_doc(self):
        dup = run_source(
            src(
                """
                class AmId:
                    A = 0
                    B = 0
                """
            ),
            passes=["wire-schema"],
        )
        assert len(dup) == 1 and "duplicate values" in dup[0].message
        gap = run_source(
            src(
                """
                class AmId:
                    A = 0
                    B = 2
                """
            ),
            passes=["wire-schema"],
        )
        assert len(gap) == 1 and "not contiguous" in gap[0].message

    def test_doc_checks_skipped_without_doc(self):
        # installed-package runs have no docs/; the shape checks still run
        findings = run_source(src(WIRE_FIXTURE), passes=["wire-schema"])
        assert findings == []

    def test_extractors_roundtrip(self):
        from sparkucx_tpu.analysis.protocol import camel, extract_am_ids, extract_structs

        assert extract_am_ids(src(WIRE_FIXTURE)) == {"FETCH_REQ": 0, "FETCH_ACK": 1}
        assert extract_structs(src(WIRE_FIXTURE)) == {"_HDR": "<IQQ"}
        assert camel("REPLICA_PUT") == "ReplicaPut"
        assert camel("MEMBER_SUSPECT") == "MemberSuspect"


# ----------------------------------------------------------------------
# conf-registry (whole-program pass; docs + tests text injected)


CONF_FIXTURE = """
class Conf:
    alpha: int = 0
    beta: bool = False

    @classmethod
    def from_spark_conf(cls, conf):
        out = cls()
        for name, attr, conv in [
            ("alpha", "alpha", int),
            ("beta.enabled", "beta", bool),
            ("gamma", "gamma_typo", int),
        ]:
            pass
        return out
"""


class TestConfRegistry:
    def test_flags_typo_field_missing_doc_and_missing_test(self):
        findings = run_source(
            src(CONF_FIXTURE),
            passes=["conf-registry"],
            docs={"DEPLOYMENT.md": "| `spark.shuffle.tpu.alpha` | 0 | the alpha |\n"},
            tests_text="conf.alpha == 3",
        )
        msgs = messages(findings)
        assert any("unknown conf field 'gamma_typo'" in m for m in msgs)
        assert any("'spark.shuffle.tpu.beta.enabled' has no DEPLOYMENT.md row" in m for m in msgs)
        assert any("'spark.shuffle.tpu.beta.enabled'" in m and "no test" in m for m in msgs)
        assert not any("alpha" in m and "no test" in m for m in msgs)

    def test_fully_registered_clean(self):
        findings = run_source(
            src(
                """
                class Conf:
                    alpha: int = 0

                    @classmethod
                    def from_spark_conf(cls, conf):
                        out = cls()
                        for name, attr, conv in [("alpha", "alpha", int)]:
                            pass
                        return out
                """
            ),
            passes=["conf-registry"],
            docs={"DEPLOYMENT.md": "| `spark.shuffle.tpu.alpha` | 0 | the alpha |\n"},
            tests_text="spark.shuffle.tpu.alpha",
        )
        assert findings == []

    def test_off_path_default_drift_flagged(self):
        # `elastic` is pinned False in OFF_PATH_DEFAULTS: a fixture class
        # defaulting it True is exactly the flipped-default drift the pass
        # exists to catch
        findings = run_source(
            src(
                """
                class Conf:
                    elastic: bool = True

                    @classmethod
                    def from_spark_conf(cls, conf):
                        return cls()
                """
            ),
            passes=["conf-registry"],
        )
        assert len(findings) == 1
        assert "off-path default drift" in findings[0].message
        assert "'elastic'" in findings[0].message

    def test_fixture_subset_no_stale_pin_noise(self):
        # only the real config.py owes every pinned field; a fixture class
        # defining one knob must not spray "stale pin" findings
        findings = run_source(
            src(
                """
                class Conf:
                    alpha: int = 0

                    @classmethod
                    def from_spark_conf(cls, conf):
                        return cls()
                """
            ),
            passes=["conf-registry"],
        )
        assert findings == []


# ----------------------------------------------------------------------
# lockstep-taint


class TestLockstepTaint:
    def test_flags_direct_telemetry_into_collective_field(self):
        findings = run_source(
            src(
                """
                def plan(registry, plan):
                    snap = registry.snapshot()
                    return replace(plan, chunks_per_round=snap["depth"])
                """
            ),
            passes=["lockstep-taint"],
        )
        assert len(findings) == 1
        assert "chunks_per_round" in findings[0].message
        assert "local telemetry" in findings[0].message

    def test_flags_transitive_helper_flow(self):
        # the satellite-required case: telemetry flows through a module
        # helper before reaching chunks_per_round
        findings = run_source(
            src(
                """
                def _derive(stall_ns):
                    return 2 if stall_ns > 1000 else 8

                def plan(registry, plan):
                    snap = registry.snapshot()
                    depth = _derive(snap["rx_stall_p99_ns"])
                    return replace(plan, chunks_per_round=depth)
                """
            ),
            passes=["lockstep-taint"],
        )
        assert len(findings) == 1
        assert "chunks_per_round" in findings[0].message

    def test_flags_closure_helper_flow(self):
        # nested def capturing tainted state from the enclosing scope
        findings = run_source(
            src(
                """
                def plan(registry, plan):
                    snap = registry.snapshot()

                    def pick():
                        return snap["depth"] + 1

                    return replace(plan, chunks_per_round=pick())
                """
            ),
            passes=["lockstep-taint"],
        )
        assert len(findings) == 1
        assert "chunks_per_round" in findings[0].message

    def test_flags_collective_rewrite_under_tainted_branch(self):
        # implicit flow: the VALUE is a constant but the rewrite only
        # happens on hosts whose local telemetry crossed a threshold
        findings = run_source(
            src(
                """
                def plan(registry, plan):
                    sig = registry.snapshot()
                    if sig["padding"] > 0.5:
                        plan = replace(plan, chunks_per_round=4)
                    return plan
                """
            ),
            passes=["lockstep-taint"],
        )
        assert len(findings) == 1
        assert "telemetry-tainted branch" in findings[0].message

    def test_serve_plane_steering_clean(self):
        # the satellite-required clean fixture: telemetry may steer
        # hedge_ms/streams freely (serve-plane), and the resulting plan
        # object stays clean (absorption)
        findings = run_source(
            src(
                """
                def plan(registry, plan):
                    sig = registry.snapshot()
                    hedge = 5 if sig["rx_stall_p99_ns"] else 0
                    plan = replace(plan, hedge_ms=hedge)
                    if sig["credit_stall_ns"]:
                        plan = replace(plan, streams=2)
                    return replace(plan, chunks_per_round=8)
                """
            ),
            passes=["lockstep-taint"],
        )
        assert findings == []

    def test_conf_and_geometry_clean(self):
        # conf fields and all-gathered geometry are the sanctioned inputs
        findings = run_source(
            src(
                """
                def plan(conf, gathered_rows, plan):
                    rows = int(gathered_rows.max())
                    return replace(
                        plan,
                        chunks_per_round=conf.exchange_chunks_per_round,
                        slot_rows=rows,
                        lowering=conf.exchange_impl,
                    )
                """
            ),
            passes=["lockstep-taint"],
        )
        assert findings == []

    def test_escape_comment(self):
        findings = run_source(
            src(
                """
                def plan(registry, plan):
                    snap = registry.snapshot()
                    return replace(plan, chunks_per_round=snap["d"])  #: lockstep-ok reviewed
                """
            ),
            passes=["lockstep-taint"],
        )
        assert findings == []

    def test_precollective_branch_flagged_raise_exempt(self):
        findings = run_source(
            src(
                """
                def run_exchange(self):
                    snap = self.membership.snapshot()
                    if snap["dead"]:
                        raise RuntimeError("executor lost")
                    if snap["slow"]:
                        self.use_degraded_schedule()
                    self.collective()
                """
            ),
            passes=["lockstep-taint"],
        )
        assert len(findings) == 1
        assert "pre-collective branch" in findings[0].message
        assert findings[0].line == 6  # the schedule branch, not the raise

    def test_registry_partitions_exchange_plan(self):
        # acceptance criterion: COLLECTIVE_FIELDS == ExchangePlan fields
        # minus the declared serve-plane fields, with no overlap
        import dataclasses

        from sparkucx_tpu.analysis.config import (
            COLLECTIVE_FIELDS,
            SERVE_PLANE_FIELDS,
        )
        from sparkucx_tpu.ops.skew import ExchangePlan

        fields = {f.name for f in dataclasses.fields(ExchangePlan)}
        assert set(COLLECTIVE_FIELDS) | set(SERVE_PLANE_FIELDS) == fields
        assert not set(COLLECTIVE_FIELDS) & set(SERVE_PLANE_FIELDS)
        assert set(COLLECTIVE_FIELDS) == fields - set(SERVE_PLANE_FIELDS)

    def test_registry_drift_flagged(self):
        # a plan field the registry never classified must fail the run —
        # the fixture poses as ops/skew.py so the dataclass cross-check fires
        findings = run_source(
            src(
                """
                class ExchangePlan:
                    slot_rows: int
                    chunks_per_round: int
                    single_shot: bool
                    round_order: tuple
                    lowering: str
                    pipeline_depth: int
                    streams: int
                    codec: str
                    quantize_mode: str
                    quantize_block: int
                    hedge_ms: int
                    combine: str
                    mystery_knob: int
                """
            ),
            passes=["lockstep-taint"],
            filename="ops/skew.py",
        )
        assert len(findings) == 1
        assert "mystery_knob" in findings[0].message
        assert "neither COLLECTIVE_FIELDS nor SERVE_PLANE_FIELDS" in findings[0].message

    def test_real_planner_and_spmd_transport_pass(self, capsys):
        # the real AdaptivePlanner steers serve-plane fields from telemetry
        # and the SPMD transport fail-fasts on membership — both must be
        # clean under the pass (acceptance criterion)
        assert analysis_main(["--ci", "--passes", "lockstep-taint"]) == 0
        assert capsys.readouterr().out == ""

    def test_injected_regression_in_real_planner_caught(self):
        # mutate the REAL planner source: steering chunks_per_round from
        # PlanSignals telemetry must flag, at the mutated line — proving
        # the pass guards the actual code, not just toy fixtures
        import sparkucx_tpu.ops.planner as planner_mod

        src = open(planner_mod.__file__).read()
        needle = "plan = dataclasses.replace(plan, hedge_ms=hedge)"
        assert needle in src  # the serve-plane hedge steer in AdaptivePlanner
        mutated = src.replace(
            needle,
            "plan = dataclasses.replace(plan, hedge_ms=hedge, "
            "chunks_per_round=(1 + int(sig.rx_stall_p99_ns > 0),))",
        )
        findings = run_source(
            mutated, passes=["lockstep-taint"], filename="ops/planner.py"
        )
        assert len(findings) == 1
        assert "chunks_per_round" in findings[0].message
        # implicit flow too: widening a serve-plane rewrite that sits under
        # a telemetry branch with a collective field
        mutated2 = src.replace(
            'plan = dataclasses.replace(plan, codec="off")',
            'plan = dataclasses.replace(plan, codec="off", single_shot=True)',
        )
        assert mutated2 != src
        findings2 = run_source(
            mutated2, passes=["lockstep-taint"], filename="ops/planner.py"
        )
        assert len(findings2) == 1
        assert "single_shot" in findings2[0].message
        assert "telemetry-tainted branch" in findings2[0].message


# ----------------------------------------------------------------------
# span-discipline


class TestSpanDiscipline:
    def test_flags_discarded_span(self):
        findings = run_source(
            src(
                """
                def serve(tracer):
                    tracer.start_span("server.serve")
                """
            ),
            passes=["span-discipline"],
        )
        assert len(findings) == 1
        assert "discarded" in findings[0].message

    def test_flags_span_not_closed_in_finally(self):
        findings = run_source(
            src(
                """
                def serve(tracer):
                    ctx = tracer.start_span("server.serve")
                    do_work()
                    tracer.end_span(ctx)
                """
            ),
            passes=["span-discipline"],
        )
        assert len(findings) == 1
        assert "closed on all paths" in findings[0].message

    def test_finally_closed_clean(self):
        findings = run_source(
            src(
                """
                def serve(tracer):
                    ctx = tracer.start_span("server.serve")
                    try:
                        do_work()
                    finally:
                        tracer.end_span(ctx)
                """
            ),
            passes=["span-discipline"],
        )
        assert findings == []

    def test_handoff_requires_docstring(self):
        flagged = run_source(
            src(
                """
                def open_window(tracer):
                    return tracer.start_span("read.window")
                """
            ),
            passes=["span-discipline"],
        )
        assert len(flagged) == 1
        assert "docstring" in flagged[0].message
        clean = run_source(
            src(
                '''
                def open_window(tracer):
                    """Open the window span; ended by close_window."""
                    return tracer.start_span("read.window")
                '''
            ),
            passes=["span-discipline"],
        )
        assert clean == []

    def test_instant_names_checked_against_doc(self):
        doc = {"OBSERVABILITY.md": "| `exchange.plan` | planner resolved |"}
        flagged = run_source(
            src(
                """
                def f():
                    instant("exchange.bogus")
                """
            ),
            passes=["span-discipline"],
            docs=doc,
        )
        assert len(flagged) == 1
        assert "exchange.bogus" in flagged[0].message
        clean = run_source(
            src(
                """
                def f():
                    instant("exchange.plan")
                """
            ),
            passes=["span-discipline"],
            docs=doc,
        )
        assert clean == []

    def test_escape_comment(self):
        findings = run_source(
            src(
                """
                def serve(tracer):
                    tracer.start_span("fire.and.forget")  #: span-ok sampled externally
                """
            ),
            passes=["span-discipline"],
        )
        assert findings == []


# ----------------------------------------------------------------------
# metrics-naming


class TestMetricsNaming:
    DOC = {"OBSERVABILITY.md": "| `ops` | stats |\n| `wire` | lanes |\n"}

    def test_flags_bad_family_and_name(self):
        findings = run_source(
            src(
                """
                def provide():
                    return [sample("Bad-Family", "x", 1)]
                """
            ),
            passes=["metrics-naming"],
        )
        assert any("Bad-Family" in m for m in messages(findings))
        findings = run_source(
            src(
                """
                def provide():
                    return [sample("ops", "camelCase", 1)]
                """
            ),
            passes=["metrics-naming"],
        )
        assert any("snake_case" in m for m in messages(findings))

    def test_undocumented_family_flagged(self):
        findings = run_source(
            src(
                """
                def provide():
                    return [sample("ghost", "x_total", 1)]
                """
            ),
            passes=["metrics-naming"],
            docs=self.DOC,
        )
        assert any(
            "ghost" in m and "no row" in m for m in messages(findings)
        )

    def test_documented_families_clean_and_stale_row_flagged(self):
        findings = run_source(
            src(
                """
                def wire_up(reg):
                    reg.register("ops", counter_dict_provider("ops", get))
                    return sample("wire", "tx_bytes_total", 1)
                """
            ),
            passes=["metrics-naming"],
            docs=self.DOC,
        )
        assert findings == []
        # drop the wire registration: its doc row is now stale
        findings = run_source(
            src(
                """
                def wire_up(reg):
                    reg.register("ops", counter_dict_provider("ops", get))
                """
            ),
            passes=["metrics-naming"],
            docs=self.DOC,
        )
        assert any(
            "wire" in m and "stale" in m for m in messages(findings)
        )

    def test_escape_comment(self):
        findings = run_source(
            src(
                """
                def provide():
                    return [sample("Legacy-Fam", "x", 1)]  #: metric-ok grandfathered
                """
            ),
            passes=["metrics-naming"],
        )
        assert findings == []


# ----------------------------------------------------------------------
# error-taxonomy


class TestErrorTaxonomy:
    API = {"API.md": "BlockNotFoundError UnknownTenantError retryable fail-fast"}

    def test_retry_path_catching_fail_fast_flagged(self):
        findings = run_source(
            src(
                """
                def _retry_fetch(self):
                    try:
                        fetch()
                    except ExecutorLostError:
                        pass
                """
            ),
            passes=["error-taxonomy"],
        )
        assert len(findings) == 1
        assert "ExecutorLostError" in findings[0].message
        assert "fail-fast" in findings[0].message

    def test_broad_catch_without_guards_flagged(self):
        findings = run_source(
            src(
                """
                def _retry_fetch(self):
                    try:
                        fetch()
                    except TransportError:
                        pass
                """
            ),
            passes=["error-taxonomy"],
        )
        assert len(findings) == 1
        assert "silently retried" in findings[0].message

    def test_broad_catch_with_tuple_guard_clean(self):
        # the reader idiom: one module-level fail-fast tuple, isinstance +
        # re-raise inside the broad handler
        findings = run_source(
            src(
                """
                _FF = (TenantQuotaExceededError, UnknownTenantError, ExecutorLostError)

                def _retry_fetch(self):
                    try:
                        fetch()
                    except TransportError as e:
                        if isinstance(e, _FF):
                            raise
                """
            ),
            passes=["error-taxonomy"],
        )
        assert findings == []

    def test_unclassified_subclass_flagged(self):
        findings = run_source(
            src(
                """
                class TransportError(RuntimeError):
                    pass

                class NewFangledError(TransportError):
                    pass
                """
            ),
            passes=["error-taxonomy"],
            filename="core/operation.py",
        )
        assert any(
            "NewFangledError" in m and "not classified" in m
            for m in messages(findings)
        )

    def test_stale_taxonomy_entry_flagged(self):
        # a registry entry whose class was deleted must fail
        findings = run_source(
            src(
                """
                class TransportError(RuntimeError):
                    pass
                """
            ),
            passes=["error-taxonomy"],
            filename="core/operation.py",
        )
        assert any("stale registry entry" in m for m in messages(findings))

    def test_escape_comment(self):
        findings = run_source(
            src(
                """
                def _retry_fetch(self):
                    try:
                        fetch()
                    except ExecutorLostError:  #: taxonomy-ok reviewed special case
                        pass
                """
            ),
            passes=["error-taxonomy"],
        )
        assert findings == []

    def test_real_taxonomy_classifies_every_subclass(self, capsys):
        assert analysis_main(["--ci", "--passes", "error-taxonomy"]) == 0
        assert capsys.readouterr().out == ""


# ----------------------------------------------------------------------
# tier-vocabulary


class TestTierVocabulary:
    def test_flags_drifted_compare_literal(self):
        findings = run_source(
            src(
                """
                def pick(conf):
                    if conf.quantize_mode == "bf16":
                        return fancy()
                """
            ),
            passes=["tier-vocabulary"],
        )
        assert len(findings) == 1
        assert "'bf16'" in findings[0].message

    def test_flags_drifted_keyword_and_membership(self):
        findings = run_source(
            src(
                """
                def build(plan):
                    if plan.codec in ("off", "zstd"):
                        return None
                    return compile_exchange(lowering="fast")
                """
            ),
            passes=["tier-vocabulary"],
        )
        assert len(messages(findings)) == 2
        assert any("'zstd'" in m for m in messages(findings))
        assert any("'fast'" in m for m in messages(findings))

    def test_vocabulary_literals_clean(self):
        findings = run_source(
            src(
                """
                def pick(conf, plan):
                    lowering = "stock"
                    if conf.exchange_impl in ("pallas", "auto"):
                        lowering = "pallas"
                    return replace(plan, lowering=lowering, combine="sorted")
                """
            ),
            passes=["tier-vocabulary"],
        )
        assert findings == []

    def test_escape_comment(self):
        findings = run_source(
            src(
                """
                def pick(conf):
                    return conf.codec == "experimental"  #: tier-ok staged rollout
                """
            ),
            passes=["tier-vocabulary"],
        )
        assert findings == []

    def test_doc_vocabulary_enumerated(self):
        # a doc missing a documented knob's tier value must flag
        findings = run_source(
            "x = 1\n",
            passes=["tier-vocabulary"],
            docs={"DEPLOYMENT.md": "| `quantize.mode` | off | `int8` only |"},
        )
        assert any("blockfloat" in m for m in messages(findings))


# ----------------------------------------------------------------------
# CLI


class TestCli:
    def test_ci_clean_at_head(self, capsys):
        assert analysis_main(["--ci"]) == 0
        assert capsys.readouterr().out == ""

    def test_injected_violation_fails_with_file_line(self, tmp_path, capsys):
        bad = tmp_path / "leaky.py"
        bad.write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._q = []  #: guarded by self._lock\n"
            "    def leak(self, x):\n"
            "        self._q.append(x)\n"
        )
        assert analysis_main(["--ci", "--root", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "leaky.py:7" in out
        assert "[lock-discipline]" in out

    def test_unknown_pass_rejected(self, capsys):
        assert analysis_main(["--passes", "nope"]) == 2

    def test_list_passes(self, capsys):
        assert analysis_main(["--list-passes"]) == 0
        out = capsys.readouterr().out.split()
        for name in (
            "use-after-donate",
            "lock-discipline",
            "host-sync",
            "cache-hygiene",
            "private-access",
            "required-surface",
            "lock-order",
            "reactor-discipline",
            "thread-lifecycle",
            "resource-balance",
            "wire-schema",
            "conf-registry",
            "lockstep-taint",
            "span-discipline",
            "metrics-naming",
            "error-taxonomy",
            "tier-vocabulary",
        ):
            assert name in out

    def test_stale_allowlist_entry_fails_full_run(self, capsys, monkeypatch):
        import sparkucx_tpu.analysis.__main__ as cli

        stale = ("no/such_file.py", "lock-discipline", "never-matches-anything")
        monkeypatch.setattr(cli, "ALLOWLIST", cli.ALLOWLIST | {stale})
        assert analysis_main([]) == 1
        err = capsys.readouterr().err
        assert "stale allowlist entry" in err
        assert "never-matches-anything" in err

    def test_stale_builder_table_entry_fails_full_run(self, capsys, monkeypatch):
        # PR 10 policy extended to the function-pinning tables: a donation
        # entry for a deleted builder (the PR 13 `_run_exchange_quota`
        # cleanup) must fail the default run, not silently match nothing
        import sparkucx_tpu.analysis.__main__ as cli

        monkeypatch.setattr(
            cli,
            "DONATING_BUILDERS",
            {**cli.DONATING_BUILDERS, "_run_exchange_quota": (0,)},
        )
        assert analysis_main([]) == 1
        err = capsys.readouterr().err
        assert "stale DONATING_BUILDERS entry" in err
        assert "_run_exchange_quota" in err

    def test_stale_host_sync_root_fails_full_run(self, capsys, monkeypatch):
        import sparkucx_tpu.analysis.__main__ as cli

        monkeypatch.setattr(
            cli, "HOST_SYNC_ROOTS", cli.HOST_SYNC_ROOTS + ("_assemble",)
        )
        assert analysis_main([]) == 1
        err = capsys.readouterr().err
        assert "stale HOST_SYNC_ROOTS entry" in err
        assert "_assemble" in err

    def test_dump_lock_graph(self, capsys):
        assert analysis_main(["--dump-lock-graph"]) == 0
        out = capsys.readouterr().out
        assert out.startswith("digraph lock_order")
        # the store lock nests inside the transport's tag lock, and the
        # tenant registry lock inside the store lock — the documented chain
        assert '"HbmBlockStore._lock" -> "TenantRegistry._lock"' in out

    def test_tests_tree_private_access_clean(self):
        from sparkucx_tpu.analysis.base import repo_root

        tests_dir = os.path.join(repo_root(), "tests")
        assert analysis_main(
            ["--ci", "--root", tests_dir, "--passes", "private-access",
             "--allowlist", "tests"]
        ) == 0


# ----------------------------------------------------------------------
# runtime buffer sanitizer


@pytest.fixture
def sane_pool():
    pool = MemoryPool(TpuShuffleConf(sanitize=True))
    yield pool
    try:
        pool.close()
    except ResourceWarning:
        pass


class TestSanitizer:
    def test_conf_knob(self):
        assert MemoryPool(TpuShuffleConf()).sanitizer.enabled is False
        assert MemoryPool(TpuShuffleConf(sanitize=True)).sanitizer.enabled is True
        conf = TpuShuffleConf.from_spark_conf({"spark.shuffle.tpu.sanitize": "true"})
        assert conf.sanitize is True

    def test_double_release_raises(self, sane_pool):
        mb = sane_pool.get(100)
        mb.close()
        with pytest.raises(SanitizerError, match="double release"):
            mb.close()

    def test_normal_mode_release_idempotent(self):
        pool = MemoryPool(TpuShuffleConf())
        mb = pool.get(100)
        mb.close()
        mb.close()  # documented no-op
        pool.close()

    def test_freed_buffer_poisoned(self, sane_pool):
        mb = sane_pool.get(64)
        mb.host_view()[:] = 7
        backing = mb.data
        mb.close()
        assert (np.asarray(backing).reshape(-1).view(np.uint8) == POISON).all()
        assert sane_pool.sanitizer.stats()["poisoned_bytes"] > 0

    def test_use_after_release_raises(self, sane_pool):
        mb = sane_pool.get(32)
        r = BlockFetchResult(
            ShuffleBlockId(1, 2, 3),
            memoryview(mb.host_view()),
            mb,
            pooled=True,
            sanitizer=sane_pool.sanitizer,
        )
        r.release()
        with pytest.raises(SanitizerError, match="use-after-release"):
            r.data
        # detach/release stay idempotent even in sanitize mode: the fetch
        # iterator's `finally: prev.detach()` safety net relies on it
        r.detach()
        r.release()

    def test_repool_with_live_view_raises_then_recovers(self, sane_pool):
        mb = sane_pool.get(32)
        r = BlockFetchResult(
            ShuffleBlockId(1, 2, 3),
            memoryview(mb.host_view()),
            mb,
            pooled=True,
            sanitizer=sane_pool.sanitizer,
        )
        with pytest.raises(SanitizerError, match="live exported view"):
            mb.close()
        # the failed close leaves the handle checked out; the legitimate
        # release path (view first, then buffer) still works
        r.release()

    def test_detach_keeps_data_valid(self, sane_pool):
        mb = sane_pool.get(8)
        mb.host_view()[:] = 42
        view = memoryview(mb.host_view()[: mb.size])
        r = BlockFetchResult(
            ShuffleBlockId(0, 0, 0), view, mb, pooled=True,
            sanitizer=sane_pool.sanitizer,
        )
        r.detach()
        assert bytes(r.data)[:4] == b"\x2a\x2a\x2a\x2a"

    def test_disabled_sanitizer_is_noop(self):
        san = BufferSanitizer(enabled=False)
        san.on_checkout(object())
        san.on_double_release(object())
        san.check_view_released("anything")
        assert san.stats()["checkouts"] == 0
