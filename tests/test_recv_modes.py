"""host_recv_mode: the post-exchange host-memory budget (SURVEY §7 "HBM
budget", host half; VERDICT r4 item 8).

'array' keeps a RAM copy per round (the historical behavior), 'memmap' spills
each round's received shards to disk and serves fetches through read-only
``np.memmap`` views, 'device' keeps no host copy at all and slices the
HBM-resident shard per fetch."""

import os



import numpy as np
import pytest

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import OperationStatus, TransportError
from sparkucx_tpu.transport.tpu import TpuShuffleCluster

N_EXEC = 4


def _buf(n):
    return MemoryBlock(np.zeros(n, dtype=np.uint8), size=n)


def _write_shuffle(cluster, shuffle_id, M, R, rng, block=2000):
    meta = cluster.create_shuffle(shuffle_id, M, R)
    oracle = {}
    for m in range(M):
        t = cluster.transport(meta.map_owner[m])
        w = t.store.map_writer(shuffle_id, m)
        for r in range(R):
            payload = rng.integers(0, 256, size=block, dtype=np.uint8).tobytes()
            oracle[(m, r)] = payload
            w.write_partition(r, payload)
        t.commit_block(w.commit().pack())
    return meta, oracle


def _fetch_all(cluster, meta, shuffle_id, M, R, oracle):
    for r in range(R):
        consumer = meta.owner_of_reduce(r)
        t = cluster.transport(consumer)
        bufs = [_buf(8192) for _ in range(M)]
        reqs = t.fetch_blocks_by_block_ids(
            consumer, [ShuffleBlockId(shuffle_id, m, r) for m in range(M)],
            bufs, [None] * M,
        )
        for m in range(M):
            res = reqs[m].wait(5)
            assert res.status == OperationStatus.SUCCESS, str(res.error)
            assert bufs[m].host_view()[: bufs[m].size].tobytes() == oracle[(m, r)]


class TestMemmapMode:
    def test_multi_round_vs_oracle_and_cleanup(self, rng, tmp_path):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=N_EXEC * 4096,
            block_alignment=128,
            num_executors=N_EXEC,
            host_recv_mode="memmap",
            spill_dir=str(tmp_path),
        )
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        M, R = 3 * N_EXEC, 8
        meta, oracle = _write_shuffle(cluster, 0, M, R, rng)
        cluster.run_exchange(0)
        assert len(meta.recv_shards) > 1, "test should spill multiple rounds"
        # every shard view is a read-only disk-backed mapping, not RAM
        for rnd in meta.recv_shards:
            for shard in rnd:
                assert isinstance(shard, np.memmap)
                assert not shard.flags.writeable
        spilled = [p for p, _ in meta.recv_spill_paths]
        assert spilled and all(os.path.exists(p) for p in spilled)
        _fetch_all(cluster, meta, 0, M, R, oracle)
        cluster.remove_shuffle(0)
        assert not any(os.path.exists(p) for p in spilled), "spill files leaked"


class TestMemmapDiskCap:
    def test_recv_spill_charged_against_cap(self, rng, tmp_path):
        """spill_disk_cap_bytes bounds the received-shard spill too — a
        too-small cap is a TransportError at exchange, not silent disk fill."""
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 18,
            block_alignment=128,
            num_executors=N_EXEC,
            host_recv_mode="memmap",
            spill_dir=str(tmp_path),
            spill_disk_cap_bytes=4096,  # far below one received round
        )
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        _write_shuffle(cluster, 0, 4, 4, rng, block=512)
        with pytest.raises(TransportError, match="spill_disk_cap_bytes"):
            cluster.run_exchange(0)

    def test_cap_released_on_remove(self, rng, tmp_path):
        """remove_shuffle returns its spill bytes to the budget."""
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 18,
            block_alignment=128,
            num_executors=N_EXEC,
            host_recv_mode="memmap",
            spill_dir=str(tmp_path),
            spill_disk_cap_bytes=16 << 20,  # fits one shuffle, not two
        )
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        for sid in range(3):  # three sequential shuffles reuse the budget
            meta, oracle = _write_shuffle(cluster, sid, 4, 4, rng, block=512)
            cluster.run_exchange(sid)
            _fetch_all(cluster, meta, sid, 4, 4, oracle)
            cluster.remove_shuffle(sid)
        assert cluster._recv_spill_bytes == 0


class TestDeviceMode:
    def test_no_host_copy_vs_oracle(self, rng):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 18,
            block_alignment=128,
            num_executors=N_EXEC,
            host_recv_mode="device",
            keep_device_recv=True,
        )
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        M, R = 8, 8
        meta, oracle = _write_shuffle(cluster, 0, M, R, rng)
        cluster.run_exchange(0)
        assert meta.recv_shards is None, "device mode must keep no host copy"
        assert meta.recv_device is not None
        _fetch_all(cluster, meta, 0, M, R, oracle)

    def test_requires_keep_device_recv(self, rng):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 18,
            block_alignment=128,
            num_executors=N_EXEC,
            host_recv_mode="device",
        )
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        meta, _ = _write_shuffle(cluster, 0, 2, 2, rng, block=64)
        with pytest.raises(TransportError, match="keep_device_recv"):
            cluster.run_exchange(0)

    def test_unknown_mode_rejected(self, rng):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=1 << 18,
            num_executors=N_EXEC,
            host_recv_mode="ram",
        )
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        _write_shuffle(cluster, 0, 2, 2, rng, block=64)
        with pytest.raises(ValueError, match="host_recv_mode"):
            cluster.run_exchange(0)


class TestHostBudgetStructural:
    """The budget claim in structural form.  A direct ru_maxrss comparison is
    NOT meaningful on this virtual CPU mesh: ``np.asarray`` of a cpu-backend
    jax shard is zero-copy (the 'array'-mode host shards alias the jax
    buffers that exist in both modes), and XLA:CPU's pooled allocator never
    returns freed pages to the OS, so peak RSS measures the allocator
    high-water mark, not retention (measured: 653 vs 620 MiB for a 160 MiB
    dataset).  On real TPU hardware the D2H in 'array' mode is a genuine host
    copy per round — what 'memmap'/'device' eliminate.  What CAN be asserted
    portably: after a multi-round memmap exchange, every retained recv shard
    is file-backed (zero RAM-backed recv bytes), their file sizes cover the
    received data, and fetches never resurrect a RAM copy."""

    def test_memmap_retains_zero_ram_backed_recv_bytes(self, rng, tmp_path):
        conf = TpuShuffleConf(
            staging_capacity_per_executor=N_EXEC * 4096,
            block_alignment=128,
            num_executors=N_EXEC,
            host_recv_mode="memmap",
            spill_dir=str(tmp_path),
        )
        cluster = TpuShuffleCluster(conf, num_executors=N_EXEC)
        M, R = 3 * N_EXEC, 8
        meta, oracle = _write_shuffle(cluster, 0, M, R, rng)
        cluster.run_exchange(0)
        assert len(meta.recv_shards) >= 3, "should spill multiple rounds"
        ram_backed = sum(
            shard.nbytes
            for rnd in meta.recv_shards
            for shard in rnd
            if not isinstance(shard, np.memmap)
        )
        assert ram_backed == 0, f"{ram_backed} recv bytes retained in RAM"
        on_disk = sum(os.path.getsize(p) for p, _ in meta.recv_spill_paths)
        received = sum(int(s.sum()) for s in meta.recv_sizes) * conf.block_alignment
        assert on_disk >= received > 0
        assert cluster._recv_spill_bytes == on_disk
        # fetches serve from the mappings without converting them to arrays
        _fetch_all(cluster, meta, 0, M, R, oracle)
        assert all(
            isinstance(shard, np.memmap)
            for rnd in meta.recv_shards
            for shard in rnd
        )
